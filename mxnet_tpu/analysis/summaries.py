"""mxflow's per-function effect summaries.

Every interprocedural rule consumes the same two layers built here:

**Direct facts** (:class:`FunctionFacts`) — one AST pass per file,
node-free and keyed by qualname so they are CACHEABLE across runs in
one process (``_FACTS_CACHE``, keyed on the file's display path + a
content hash; ``cache_stats()`` reports hits/misses and the unit tests
pin the behaviour). Per function:

* blocking host syncs (``.asnumpy()`` / ``.wait_to_read()`` /
  ``np.asarray`` over a non-literal) with line + form;
* nonlocal mutations: stores to ``self.<attr>``, to subscripts/
  attributes of non-local names, to ``global``/``nonlocal`` declared
  names, and mutating method calls (``append``/``update``/...) on
  nonlocal receivers;
* wall-clock reads (``time.time``-family, ``datetime.now``), global
  RNG draws (``random.*``, ``np.random.*``, ``uuid``/``secrets``) and
  telemetry calls (anything resolving into ``mxnet_tpu.telemetry``) —
  the trace-purity facts: each of these, executed under a trace,
  freezes one stale value into every future run of the compiled
  program;
* locks acquired, every ``self.<attr>`` access with the lockset
  lexically held at it, and the lockset held at every call site (the
  RacerD-style lockset rule's raw material);
* donation plumbing: literal ``donate_argnums`` jit calls, local
  names bound to them, call-through-name sites, return-value flow;
* exception flow (the mxlife raw material): ``raise`` statements not
  swallowed by an enclosing try-with-handlers, and the set of call
  sites whose exceptions ARE swallowed (``guarded_calls``) — a try
  with ANY except handler is treated as guarding its try body
  (conservative-quiet: a typed handler that would miss the callee's
  class never fabricates a finding); handler/else/finally bodies are
  NOT guarded by their own try.

**Transitive layer** (:class:`Summaries`) — graph-dependent, computed
per run over the :mod:`~.callgraph` with worklist/BFS fixpoints (so
recursion/SCCs terminate and propagate correctly, callees before
callers):

* ``sync_witnesses(fn)`` — EVERY sync-bearing function reachable from
  ``fn`` over ``call`` edges only (ref edges excluded: a callback
  handed to the resolver pool blocks on its own thread, legally), each
  with a shortest witness chain and ALL of its blocking-fetch sites —
  enumerating every site means a justified disable on one sync line
  never hides an unjustified sync on the next, and a fully-suppressed
  near sink never hides a farther one;
* ``donates_params(fn)`` — the param positions a function passes on
  at a donated position of some donated program (directly or through
  callees), which is what lets callers drop their manual
  ``# mxlint: donates`` markers;
* ``returns_donating(fn)`` — functions whose RETURN VALUE is a
  donating program (``return jax.jit(..., donate_argnums=...)`` or a
  callee that does), so ``fn = self._build_step(...); fn(w, s)`` is
  recognized as a donating call with no marker;
* ``donated_sites(fn)`` — every call site in ``fn`` with inferred
  donated positions, in call-site positional terms (bound-method
  shifts applied) — the donation rule's interprocedural feed;
* ``may_raise(fn)`` / ``raise_chain(fn)`` — whether an exception can
  escape ``fn`` (an unguarded own ``raise``, or an unguarded call
  site reaching a may-raise callee, transitively over ``call`` edges
  via one reverse-BFS propagation), with the witness chain down to
  the origin ``raise``. ONLY in-scan raises seed it — an external
  callee (stdlib, jax) never fabricates a raise edge, the same
  certainty contract as the call graph's.
"""
from __future__ import annotations

import ast
from collections import deque

from . import callgraph as cg
from .core import expr_text, resolve_origin

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

_BLOCKING_METHODS = {"asnumpy", "wait_to_read"}
_HOST_LITERALS = (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.ListComp,
                  ast.SetComp, ast.DictComp, ast.GeneratorExp,
                  ast.Constant)


def classify_sync_call(node, np_names, asarray_names):
    """The blocking form of an ``ast.Call`` — ``'.asnumpy()'`` /
    ``'.wait_to_read()'`` / ``'np.asarray(...)'`` — or None.
    ``np.asarray`` over an obvious host literal is exempt (building a
    feed array from Python scalars is host work, not a device sync).
    ONE classifier feeding both the direct host-sync rule and the
    transitive facts, so a new blocking form can never be caught
    per-file yet missed through a call chain, or vice versa."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_METHODS:
        return ".%s()" % f.attr
    if ((isinstance(f, ast.Attribute) and f.attr == "asarray"
         and isinstance(f.value, ast.Name) and f.value.id in np_names)
            or (isinstance(f, ast.Name) and f.id in asarray_names)):
        if not (node.args and isinstance(node.args[0], _HOST_LITERALS)):
            return "np.asarray(...)"
    return None

_CLOCK_ORIGINS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}

# methods that mutate their receiver in place
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "add", "insert", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard",
    "clear", "sort", "reverse", "write",
}

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition"}

_JIT_ORIGINS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
                "jax.pmap"}

TELEMETRY_MODULE = "mxnet_tpu.telemetry"


def _is_rng_origin(origin):
    parts = origin.split(".")
    if parts[0] == "random" and len(parts) == 2 and parts[1][:1].islower():
        return True
    if origin.startswith("numpy.random.") and parts[-1][:1].islower():
        return True
    if origin in ("uuid.uuid1", "uuid.uuid4"):
        return True
    if parts[0] == "secrets" and len(parts) == 2:
        return True
    return False


# dotted origin under the rich (absolute + relative) import map —
# the ONE shared resolver from core
_resolve = resolve_origin


def _jit_donate_indices(call):
    """Literal donate_argnums of a call, or None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if not (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)):
                    return None
                out.append(el.value)
            return tuple(out)
        return None
    return None


class FunctionFacts:
    """Direct, node-free effect facts of ONE function (see module
    docstring). All locations are (line, col) in the defining file."""

    __slots__ = (
        "qualname", "params", "syncs", "mutations", "clock", "rng",
        "telemetry", "locks", "accesses", "calls_held",
        "jit_call_donates", "marker_donates", "calls_by_name",
        "name_bindings", "call_args", "call_form", "call_recv",
        "return_call_sites", "return_names", "local_jit_names",
        "global_accesses", "raises", "guarded_calls",
    )

    def __init__(self, qualname, params):
        self.qualname = qualname
        self.params = params            # positional param names, in order
        self.syncs = []                 # [(line, col, form)]
        self.mutations = []             # [(line, desc)]
        self.clock = []                 # [(line, origin)]
        self.rng = []                   # [(line, origin)]
        self.telemetry = []             # [(line, origin)]
        self.locks = set()              # canonical lock texts acquired
        self.accesses = []              # [(attr, line, col, is_store, held)]
        self.calls_held = {}            # (line, col) -> frozenset(held)
        self.jit_call_donates = {}      # (line, col) -> indices
        self.marker_donates = {}        # (line, col) -> indices
        self.calls_by_name = {}         # (line, col) -> local callee name
        self.name_bindings = {}         # name -> set of binding (line, col)
        self.call_args = {}             # (line, col) -> tuple of descriptors
        self.call_form = {}             # (line, col) -> "name" | "attr"
        self.call_recv = {}             # (line, col) -> dotted receiver
        self.return_call_sites = set()  # (line, col) of returned calls
        self.return_names = set()       # names returned directly
        self.local_jit_names = {}       # name -> donate indices
        # module-global touches with the lockset lexically held:
        # [(name, line, col, is_store, held)] — stores are `global`-
        # declared rebinds, writes THROUGH the global (subscript/attr
        # store, mutating method call), loads are plain reads; local
        # shadowing resolved away (mxsync's thread-race raw material)
        self.global_accesses = []
        # exception flow (mxlife): raise statements an enclosing
        # try-with-handlers does NOT swallow [(line, exc text)], and
        # the call sites whose exceptions ARE swallowed {(line, col)}
        self.raises = []
        self.guarded_calls = set()

    def impure_facts(self):
        """[(kind, line, desc)] of everything trace-purity cares
        about, in line order."""
        out = [("mutates", ln, d) for ln, d in self.mutations]
        out += [("reads-clock", ln, "%s()" % o) for ln, o in self.clock]
        out += [("reads-rng", ln, "%s()" % o) for ln, o in self.rng]
        out += [("calls-telemetry", ln, "%s()" % o)
                for ln, o in self.telemetry]
        out.sort(key=lambda t: t[1])
        return out


class _FileFacts:
    __slots__ = ("functions", "canonical", "known_locks",
                 "module_globals", "threadlocal_globals")

    def __init__(self):
        self.functions = {}             # (qualname, lineno) -> FunctionFacts
        self.canonical = {}             # lock alias text -> canonical
        self.known_locks = set()
        self.module_globals = set()     # top-level assigned names
        self.threadlocal_globals = set()  # bound to threading.local()


class _FactsWalker(ast.NodeVisitor):
    """One pass over a file, attributing effect facts to the INNERMOST
    enclosing function (nested defs own their bodies; their decorators
    and defaults evaluate in the enclosing scope)."""

    def __init__(self, src, amap, out):
        self.src = src
        self.amap = amap
        self.out = out
        self.scope_names = []
        self.stack = []                 # FunctionFacts of enclosing defs
        self.withs = []                 # canonical lock texts held
        self._guard = []                # per-frame try-with-handlers depth
        self.np_names = {n for n, o in amap.items() if o == "numpy"}
        self.asarray_names = {n for n, o in amap.items()
                              if o == "numpy.asarray"}
        # per-function bookkeeping resolved at pop time
        self._local_names = []          # stack of sets
        self._declared_global = []      # stack of sets
        self._pending = []              # stack of provisional mutations
        # provisional module-global touches: (facts, kind, name, line,
        # col, held) with kind "store" (plain rebind — real only when
        # `global`-declared), "deref" (write through the object) or
        # "load". facts is None while the entry sits in its OWN
        # frame's list; an entry the innermost frame cannot resolve
        # (not local, not declared) is passed UP with its origin facts
        # attached — a closure read of an ENCLOSING function's local
        # that shadows a module global must not be classified as a
        # global access (Python scoping walks every enclosing frame)
        self._gpending = []
        self.module_globals = out.module_globals

    # -- scope management ---------------------------------------------------
    def visit_ClassDef(self, node):
        for dec in node.decorator_list:
            self.visit(dec)
        self.scope_names.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self.scope_names.pop()

    def _visit_func(self, node):
        for dec in node.decorator_list:
            self.visit(dec)
        for d in node.args.defaults:
            self.visit(d)
        for d in node.args.kw_defaults:
            if d is not None:
                self.visit(d)
        self._note_local(node.name)     # the def binds its name here
        qual = ".".join(self.scope_names + [node.name])
        a = node.args
        params = [x.arg for x in
                  list(getattr(a, "posonlyargs", [])) + list(a.args)]
        facts = FunctionFacts(qual, params)
        local_names = set(params)
        local_names.update(x.arg for x in a.kwonlyargs)
        if a.vararg:
            local_names.add(a.vararg.arg)
        if a.kwarg:
            local_names.add(a.kwarg.arg)
        # keyed by (qualname, line): same-named defs (if/else variants,
        # property getter/setter pairs) must not alias the LAST def's
        # facts — an effect in an earlier variant would silently vanish
        self.out.functions[(qual, node.lineno)] = facts
        self.scope_names.append(node.name)
        self.stack.append(facts)
        self._local_names.append(local_names)
        self._declared_global.append(set())
        self._pending.append([])
        self._gpending.append([])
        held, self.withs = self.withs, []         # body runs later
        # the body's exception flow is its OWN: a nested def inside a
        # try body raises at CALL time, to its callers, not into the
        # lexical try it was defined under
        self._guard.append(0)
        for stmt in node.body:
            self.visit(stmt)
        self._guard.pop()
        self.withs = held
        # resolve provisional (locality-dependent) mutations now that
        # every local binding in the body has been seen
        locals_ = self._local_names.pop()
        declared = self._declared_global.pop()
        for name, line, desc in self._pending.pop():
            if name is None or name not in locals_ or name in declared:
                facts.mutations.append((line, desc))
        parent_gpending = self._gpending[-2] if len(self._gpending) > 1 \
            else None
        for tfacts, kind, name, line, col, gheld in self._gpending.pop():
            owner = tfacts if tfacts is not None else facts
            if kind == "store":
                # a plain rebind is global only when THIS frame
                # declared it (a nested def never inherits `global`)
                if tfacts is None and name in declared:
                    owner.global_accesses.append(
                        (name, line, col, True, gheld))
                continue
            if name in declared:
                owner.global_accesses.append(
                    (name, line, col, kind == "deref", gheld))
            elif name in locals_:
                pass        # a local (or closure var) shadows the global
            elif parent_gpending is not None:
                # undecided: let the enclosing frame's locals rule on it
                parent_gpending.append(
                    (owner, kind, name, line, col, gheld))
            else:
                owner.global_accesses.append(
                    (name, line, col, kind == "deref", gheld))
        self.stack.pop()
        self.scope_names.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node):
        # lambda bodies are opaque to the facts layer (no qualname);
        # visit for completeness in the ENCLOSING context minus locks
        held, self.withs = self.withs, []
        self.generic_visit(node)
        self.withs = held

    # -- locks --------------------------------------------------------------
    def visit_With(self, node):
        held = []
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            text = expr_text(item.context_expr)
            canon = self.out.canonical.get(text, text)
            held.append(canon)
            if self.stack and canon in self.out.known_locks:
                self.stack[-1].locks.add(canon)
        self.withs.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        del self.withs[len(self.withs) - len(held):]

    visit_AsyncWith = visit_With

    # -- exception flow ------------------------------------------------------
    def visit_Try(self, node):
        # ONLY the try body is guarded by the handlers; the handler
        # bodies, else and finally propagate to whatever encloses THEM
        guarded = bool(node.handlers) and bool(self._guard)
        if guarded:
            self._guard[-1] += 1
        for stmt in node.body:
            self.visit(stmt)
        if guarded:
            self._guard[-1] -= 1
        for h in node.handlers:
            self.visit(h)
        for stmt in node.orelse:
            self.visit(stmt)
        for stmt in node.finalbody:
            self.visit(stmt)

    visit_TryStar = visit_Try

    def visit_Raise(self, node):
        if self.stack and not (self._guard and self._guard[-1]):
            exc = node.exc
            text = "re-raise"
            if exc is not None:
                f = exc.func if isinstance(exc, ast.Call) else exc
                text = expr_text(f) or "re-raise"
            self.stack[-1].raises.append((node.lineno, text))
        self.generic_visit(node)

    # -- name/attr bookkeeping ----------------------------------------------
    def visit_Global(self, node):
        if self._declared_global:
            self._declared_global[-1].update(node.names)

    visit_Nonlocal = visit_Global

    def _note_local(self, name):
        if self._local_names:
            self._local_names[-1].add(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._note_local(node.id)
            # a plain store only mutates shared state when the name is
            # declared global/nonlocal — decided at function pop
            if self.stack and isinstance(node.ctx, ast.Store):
                self._maybe_global_store(node)
                if node.id in self.module_globals:
                    self._gpending[-1].append(
                        (None, "store", node.id, node.lineno,
                         node.col_offset, frozenset(self.withs)))
        elif isinstance(node.ctx, ast.Load) and self.stack \
                and node.id in self.module_globals:
            self._gpending[-1].append(
                (None, "load", node.id, node.lineno, node.col_offset,
                 frozenset(self.withs)))

    def _maybe_global_store(self, node):
        # ONLY the innermost frame: a `global`/`nonlocal` declaration
        # does not inherit into nested defs — a nested function's plain
        # store to the same name is a fresh local (this matches the
        # pop-time pending resolution, which also uses one frame)
        if self._declared_global \
                and node.id in self._declared_global[-1]:
            self.stack[-1].mutations.append(
                (node.lineno, "writes global '%s'" % node.id))

    def _in_constructor(self):
        # writes to self.<attr> inside a constructor build the object
        # being born — owned, happens-before publication, not a shared
        # mutation (the lock rules make the same exemption)
        return self.stack and self.stack[-1].qualname.rsplit(
            ".", 1)[-1] in ("__init__", "__new__", "__setstate__")

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            if self.stack:
                self.stack[-1].accesses.append(
                    (node.attr, node.lineno, node.col_offset,
                     isinstance(node.ctx, (ast.Store, ast.Del)),
                     frozenset(self.withs)))
            if self.stack and isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and not self._in_constructor():
                self.stack[-1].mutations.append(
                    (node.lineno, "writes self.%s" % node.attr))
        self.visit(node.value)

    def _mutation_base(self, node):
        """(root-name-to-check-or-None, description) when storing
        through ``node`` can mutate non-local state — None root means
        unconditional (rooted at self); (False, None) means local."""
        if isinstance(node, ast.Attribute):
            base, what = node, expr_text(node)
        elif isinstance(node, ast.Subscript):
            base, what = node.value, "%s[...]" % expr_text(node.value)
        else:
            return (False, None)
        if _rooted_at_self(base):
            return (None, "writes %s" % what)
        root = node_root_name(base)
        if root:
            return (root, "writes %s" % what)
        return (False, None)

    def visit_Assign(self, node):
        self._handle_store_targets(node.targets, node)
        self._track_bindings(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._handle_store_targets([node.target], node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._handle_store_targets([node.target], node)
        self.generic_visit(node)

    def _handle_store_targets(self, targets, node):
        if not self.stack:
            return
        for t in targets:
            for el in _flatten_targets(t):
                if isinstance(el, (ast.Attribute, ast.Subscript)) \
                        and not (isinstance(el, ast.Attribute)
                                 and isinstance(el.value, ast.Name)
                                 and el.value.id == "self"):
                    name, desc = self._mutation_base(el)
                    if desc is None:
                        continue
                    if name is None:
                        if not self._in_constructor():
                            self.stack[-1].mutations.append(
                                (node.lineno, desc))
                    else:
                        self._pending[-1].append(
                            (name, node.lineno, desc))
                        if name in self.module_globals:
                            # a write THROUGH the global's object
                            self._gpending[-1].append(
                                (None, "deref", name, el.lineno,
                                 el.col_offset,
                                 frozenset(self.withs)))
                # a subscript store through a direct self.<attr> is a
                # WRITE of that attribute for lockset purposes
                if isinstance(el, ast.Subscript) \
                        and isinstance(el.value, ast.Attribute) \
                        and isinstance(el.value.value, ast.Name) \
                        and el.value.value.id == "self":
                    self.stack[-1].accesses.append(
                        (el.value.attr, el.lineno, el.col_offset, True,
                         frozenset(self.withs)))

    def _track_bindings(self, node):
        """``name = <call>`` bookkeeping for donation/return flow."""
        if not self.stack or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            return
        facts = self.stack[-1]
        name = node.targets[0].id
        v = node.value
        if isinstance(v, ast.Call):
            key = (v.lineno, v.col_offset)
            facts.name_bindings.setdefault(name, set()).add(key)
            root = v.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and any(
                    root.id in frame for frame in self._local_names):
                return          # local shadowing jax etc.: not a jit
            origin = _resolve(v.func, self.amap)
            if origin in _JIT_ORIGINS:
                idx = _jit_donate_indices(v)
                if idx:
                    facts.local_jit_names[name] = idx

    def visit_Return(self, node):
        if self.stack and node.value is not None:
            facts = self.stack[-1]
            if isinstance(node.value, ast.Call):
                facts.return_call_sites.add(
                    (node.value.lineno, node.value.col_offset))
            elif isinstance(node.value, ast.Name):
                facts.return_names.add(node.value.id)
        self.generic_visit(node)

    # -- calls --------------------------------------------------------------
    def visit_Call(self, node):
        if self.stack:
            self._classify_call(node)
        self.generic_visit(node)

    def _classify_call(self, node):
        facts = self.stack[-1]
        key = (node.lineno, node.col_offset)
        facts.calls_held[key] = frozenset(self.withs)
        if self._guard and self._guard[-1]:
            facts.guarded_calls.add(key)
        f = node.func
        # arg descriptors (donation inference)
        descs = []
        for a in node.args:
            if isinstance(a, ast.Name):
                descs.append(("name", a.id))
            elif isinstance(a, ast.Attribute) \
                    and isinstance(a.value, ast.Name) \
                    and a.value.id == "self":
                descs.append(("attr", a.attr))
            else:
                descs.append(None)
        facts.call_args[key] = tuple(descs)
        facts.call_form[key] = "attr" if isinstance(f, ast.Attribute) \
            else "name"
        if isinstance(f, ast.Attribute):
            # receiver chain of an attr call, RAW dotted text — the
            # transitive layer resolves it to tell an unbound
            # Base.update(self, w) delegation (no binding consumed)
            # from a bound obj.update(w) call. A receiver rooted at a
            # local name, self or cls is a runtime object: not stored
            r, root = f.value, f.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) \
                    and root.id not in ("self", "cls") \
                    and not any(root.id in frame
                                for frame in self._local_names):
                recv = resolve_origin(r, {})
                if recv:
                    facts.call_recv[key] = recv
        if isinstance(f, ast.Name):
            facts.calls_by_name[key] = f.id

        marker = self.src.donates.get(node.lineno)
        if marker:
            facts.marker_donates[key] = marker

        # a call rooted at a LOCAL binding (param, assignment, loop
        # var — including one from an enclosing function) is a call on
        # some runtime object, not on the shadowed module: classifying
        # it as a global effect fabricates impurity on correct code
        # (e.g. `def helper(random): random.random()`); same class as
        # the callgraph's resolve-through-a-local fix
        root = f
        while isinstance(root, ast.Attribute):
            root = root.value
        root_shadowed = isinstance(root, ast.Name) and any(
            root.id in frame for frame in self._local_names)

        # blocking host syncs (the host-sync rule's direct facts);
        # the method forms (.asnumpy() on any receiver) stay — the
        # receiver is SUPPOSED to be a local — only the np.asarray
        # name-based form is shadow-sensitive
        form = classify_sync_call(
            node,
            frozenset() if root_shadowed else self.np_names,
            frozenset() if root_shadowed else self.asarray_names)
        if form is not None:
            facts.syncs.append((node.lineno, node.col_offset, form))

        origin = None if root_shadowed else _resolve(f, self.amap)
        if origin:
            if origin in _JIT_ORIGINS:
                idx = _jit_donate_indices(node)
                if idx:
                    facts.jit_call_donates[key] = idx
            if origin in _CLOCK_ORIGINS:
                facts.clock.append((node.lineno, origin))
            elif _is_rng_origin(origin):
                facts.rng.append((node.lineno, origin))
            elif origin == TELEMETRY_MODULE \
                    or origin.startswith(TELEMETRY_MODULE + "."):
                facts.telemetry.append((node.lineno, origin))

        # mutating method calls on non-local receivers
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            recv = f.value
            if _rooted_at_self(recv):
                if not self._in_constructor():
                    facts.mutations.append(
                        (node.lineno, "calls %s.%s()" % (expr_text(recv),
                                                         f.attr)))
                # a mutating method on a direct self.<attr> receiver
                # is a WRITE of that attribute for lockset purposes
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self":
                    facts.accesses.append(
                        (recv.attr, recv.lineno, recv.col_offset, True,
                         frozenset(self.withs)))
            else:
                root = node_root_name(recv)
                if root:
                    self._pending[-1].append(
                        (root, node.lineno,
                         "calls %s.%s()" % (expr_text(recv), f.attr)))
                    if root in self.module_globals:
                        self._gpending[-1].append(
                            (None, "deref", root, node.lineno,
                             node.col_offset, frozenset(self.withs)))


def _flatten_targets(t):
    if isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            for x in _flatten_targets(el):
                yield x
    elif isinstance(t, ast.Starred):
        for x in _flatten_targets(t.value):
            yield x
    else:
        yield t


def _rooted_at_self(node):
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


def node_root_name(node):
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _scan_locks(src, amap, out):
    """Known locks + Condition aliasing for a file (the lock-
    discipline rule keeps its own copy of this logic; this one feeds
    lockset inference and the lock-acquired summary)."""
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)):
            continue
        origin = _resolve(node.value.func, amap)
        if origin not in _LOCK_FACTORIES:
            continue
        target = node.targets[0]
        if isinstance(target, ast.Name) or (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            text = expr_text(target)
            out.known_locks.add(text)
            out.canonical.setdefault(text, text)
            if origin.endswith("Condition") and node.value.args:
                inner = expr_text(node.value.args[0])
                if inner:
                    out.canonical[text] = inner
                    out.known_locks.add(inner)


# {(display, text hash): _FileFacts}; the hit/miss counters back the
# summary-cache unit tests and the JSON report's cache stats
_FACTS_CACHE = {}
_CACHE_STATS = {"hits": 0, "misses": 0}
_FACTS_CACHE_MAX = 4096


def _scan_module_globals(src, amap, out):
    """Top-level assigned names — the candidate shared module state the
    thread-race rule reasons about. Names bound to ``threading.local``
    are remembered separately (thread-confined by construction)."""
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        for t in targets:
            for el in _flatten_targets(t):
                if isinstance(el, ast.Name):
                    out.module_globals.add(el.id)
                    if isinstance(node, ast.Assign) \
                            and isinstance(node.value, ast.Call) \
                            and _resolve(node.value.func, amap) \
                            == "threading.local":
                        out.threadlocal_globals.add(el.id)


def file_facts(src):
    key = (src.display, hash(src.text))
    got = _FACTS_CACHE.get(key)
    if got is not None:
        _CACHE_STATS["hits"] += 1
        return got
    _CACHE_STATS["misses"] += 1
    amap = cg._import_map(src)
    out = _FileFacts()
    _scan_locks(src, amap, out)
    _scan_module_globals(src, amap, out)
    _FactsWalker(src, amap, out).visit(src.tree)
    if len(_FACTS_CACHE) >= _FACTS_CACHE_MAX:
        _FACTS_CACHE.clear()
    _FACTS_CACHE[key] = out
    return out


def cache_stats():
    return dict(_CACHE_STATS, entries=len(_FACTS_CACHE))


class Summaries:
    """The transitive layer over one Project + CallGraph."""

    def __init__(self, project, graph):
        self.project = project
        self.graph = graph
        self._file_facts = {}           # src -> _FileFacts
        self._facts = {}                # FuncInfo -> FunctionFacts
        self._empty = FunctionFacts("<unknown>", [])
        for src in project.sources:
            self._file_facts[src] = file_facts(src)
        for fi in graph.functions:
            ff = self._file_facts[fi.src].functions.get(
                (fi.qualname, fi.node.lineno))
            self._facts[fi] = ff if ff is not None else self._empty
        self._sync_wit = {}             # FuncInfo -> witness list
        self._entry_cache = {}          # threads.entry_locksets memo
        self._may_raise = None          # FuncInfo -> origin record
        self._donates = None            # FuncInfo -> set(param idx)
        self._returns_donating = None   # FuncInfo -> indices or None
        self._donated_sites = None      # FuncInfo -> {(line,col): indices}
        self._edge_sites = {}           # FuncInfo -> {(line,col): callee}

    def facts_of(self, fi):
        return self._facts.get(fi, self._empty)

    def file_locks(self, src):
        ff = self._file_facts.get(src)
        return (ff.known_locks, ff.canonical) if ff is not None \
            else (set(), {})

    def file_globals(self, src):
        """(module-global names, thread-local-bound names) of a file —
        the thread-race rule's module-scope universe."""
        ff = self._file_facts.get(src)
        return (ff.module_globals, ff.threadlocal_globals) \
            if ff is not None else (set(), set())

    # -- transitive host-sync -----------------------------------------------
    def sync_witnesses(self, fi):
        """Every sync-bearing function reachable from ``fi`` over
        ``call`` edges (``fi`` itself included), each with a shortest
        witness chain and ALL of its sync sites:
        ``[(chain, sink_fi, [(sink_line, form), ...]), ...]`` where
        chain is [(callee FuncInfo, call line in the CALLER's file),
        ...] from ``fi`` down to the sink (empty chain = ``fi`` is the
        sink). Enumerating every reachable sink and every site — not
        just the nearest sink's first sync — is what keeps one
        justified disable from hiding a different, unjustified
        blocking fetch behind it. Forward BFS, SCC-safe, memoized per
        entry (hot entry points are few, the graph is small)."""
        cached = self._sync_wit.get(fi)
        if cached is not None:
            return cached
        pred = {fi: None}               # BFS tree: shortest chains
        order = [fi]
        queue = deque([fi])
        while queue:
            f = queue.popleft()
            for callee, line, _col in self.graph.callees(
                    f, kinds=(cg.CALL,)):
                if callee in pred:
                    continue
                pred[callee] = (f, line)
                order.append(callee)
                queue.append(callee)
        out = []
        for f in order:
            syncs = self.facts_of(f).syncs
            if not syncs:
                continue
            chain = []
            cur = f
            while pred[cur] is not None:
                parent, line = pred[cur]
                chain.append((cur, line))
                cur = parent
            chain.reverse()
            out.append((chain, f,
                        [(line, form) for line, _col, form in syncs]))
        self._sync_wit[fi] = out
        return out

    # -- exception flow (may_raise) ------------------------------------------
    def _ensure_may_raise(self):
        """One reverse-BFS propagation: functions with an unguarded own
        ``raise`` seed the set; a caller joins when SOME call site to a
        may-raise callee is unguarded (a caller whose every such site
        sits in a try-with-handlers stays out). Each member remembers
        ONE origin hop so :meth:`raise_chain` can reconstruct a real
        witness path lazily."""
        if self._may_raise is not None:
            return
        self._may_raise = {}
        queue = deque()
        for fi in self.graph.functions:
            facts = self.facts_of(fi)
            if facts.raises:
                line, exc = facts.raises[0]
                self._may_raise[fi] = ("own", line, exc)
                queue.append(fi)
        while queue:
            callee = queue.popleft()
            for caller, line, col in self.graph.callers(
                    callee, kinds=(cg.CALL,)):
                if caller in self._may_raise:
                    continue
                if (line, col) in self.facts_of(caller).guarded_calls:
                    continue
                self._may_raise[caller] = ("call", line, callee)
                queue.append(caller)

    def may_raise(self, fi):
        """True when an exception can escape ``fi`` (own unguarded
        raise, or transitively through an unguarded call site)."""
        self._ensure_may_raise()
        return fi in self._may_raise

    def raise_chain(self, fi):
        """Witness down to the origin raise:
        ``([(callee FuncInfo, call line in the CALLER's file), ...],
        origin_line, exc_text)`` — the hop list is empty when ``fi``
        itself raises. None when ``fi`` cannot raise."""
        self._ensure_may_raise()
        rec = self._may_raise.get(fi)
        if rec is None:
            return None
        hops = []
        seen = {fi}
        while rec[0] == "call":
            _kind, line, callee = rec
            hops.append((callee, line))
            if callee in seen:          # SCC safety: cut the cycle
                return (hops, rec[1], "re-raise")
            seen.add(callee)
            rec = self._may_raise[callee]
        return (hops, rec[1], rec[2])

    def may_raise_count(self):
        self._ensure_may_raise()
        return len(self._may_raise)

    # -- donation fixpoints --------------------------------------------------
    def _edges_of(self, fi):
        m = self._edge_sites.get(fi)
        if m is None:
            m = {(line, col): callee for callee, line, col
                 in self.graph.callees(fi, kinds=(cg.CALL,))}
            self._edge_sites[fi] = m
        return m

    def _site_indices(self, fi):
        """Donated positions per call site in ``fi``, in CALL-SITE
        positional terms, under the current donates/returns state.

        NOTE: jit_call_donates sites are the PROGRAM CONSTRUCTIONS
        (``jax.jit(fn, donate_argnums=...)``) — the construction call
        does not donate its own args, so it never seeds this map; it
        feeds local_jit_names / returns-donating instead."""
        facts = self.facts_of(fi)
        out = dict(facts.marker_donates)
        edges = self._edges_of(fi)
        # calls through a local name bound to a donating program
        for key, name in facts.calls_by_name.items():
            if key in out:
                continue
            idx = facts.local_jit_names.get(name)
            if idx is None:
                for bind in facts.name_bindings.get(name, ()):
                    callee = edges.get(bind)
                    if callee is not None \
                            and self._returns_donating.get(callee):
                        idx = self._returns_donating[callee]
                        break
            if idx:
                out[key] = idx
        # calls resolving to an in-scan callee that donates its params
        for key, callee in edges.items():
            if key in out:
                continue
            d = self._donates.get(callee)
            if not d:
                continue
            facts_form = facts.call_form.get(key)
            # bound-method shift: self is consumed by the binding at an
            # attribute call site — but NOT for @staticmethod, whose
            # params line up with the call args as written, and NOT
            # for an unbound Base.update(self, w) delegation, where
            # self is passed explicitly as arg 0
            shift = 1 if (callee.self_class is not None
                          and not callee.is_static
                          and facts_form == "attr"
                          and not self._class_receiver(fi, key)) else 0
            idx = tuple(sorted(i - shift for i in d if i - shift >= 0))
            if idx:
                out[key] = idx
        return out

    def _class_receiver(self, fi, key):
        """True when the attr call at ``key`` in ``fi`` has a CLASS as
        its receiver (``Base.update(self, w)`` super-delegation): the
        method is unbound, no argument is consumed by a binding."""
        recv = self.facts_of(fi).call_recv.get(key)
        if not recv:
            return False
        if "." in recv:
            head, rest = recv.split(".", 1)
            origin = self.graph.imports_of(fi.src).get(head, head)
            got = self.graph.resolve_dotted("%s.%s" % (origin, rest))
        else:
            got = self.graph.resolve_name(fi.src, fi, recv)
        return got is not None and got[0] == "class"

    def _recompute_donation(self, fi):
        """(param donations, returns-donating) of one function under
        the current state."""
        facts = self.facts_of(fi)
        edges = self._edges_of(fi)
        params = set()
        for key, idx in self._site_indices(fi).items():
            descs = facts.call_args.get(key, ())
            for i in idx:
                if i < len(descs) and descs[i] \
                        and descs[i][0] == "name" \
                        and descs[i][1] in facts.params:
                    params.add(facts.params.index(descs[i][1]))
        ret = None
        for key in facts.return_call_sites:
            if key in facts.jit_call_donates:
                ret = facts.jit_call_donates[key]
                break
            callee = edges.get(key)
            if callee is not None and self._returns_donating.get(callee):
                ret = self._returns_donating[callee]
                break
        if ret is None:
            for name in facts.return_names:
                if name in facts.local_jit_names:
                    ret = facts.local_jit_names[name]
                    break
                for bind in facts.name_bindings.get(name, ()):
                    callee = edges.get(bind)
                    if callee is not None \
                            and self._returns_donating.get(callee):
                        ret = self._returns_donating[callee]
                        break
                if ret:
                    break
        return params, ret

    def _ensure_donation(self):
        if self._donates is not None:
            return
        graph = self.graph
        self._edge_sites = {}
        self._donates = {}
        self._returns_donating = {}
        # worklist fixpoint: one pass over everything seeds the direct
        # facts; a change to a function's summary re-enqueues only its
        # CALLERS (donates/returns grow monotonically, so SCCs and
        # recursion converge)
        pending = deque(graph.functions)
        queued = set(graph.functions)
        while pending:
            fi = pending.popleft()
            queued.discard(fi)
            params, ret = self._recompute_donation(fi)
            changed = False
            if params != self._donates.get(fi, set()):
                self._donates[fi] = params
                changed = True
            if ret and not self._returns_donating.get(fi):
                self._returns_donating[fi] = ret
                changed = True
            if changed:
                for caller, _l, _c in graph.callers(fi,
                                                    kinds=(cg.CALL,)):
                    if caller not in queued:
                        pending.append(caller)
                        queued.add(caller)
        self._donated_sites = {}

    def donates_params(self, fi):
        self._ensure_donation()
        return tuple(sorted(self._donates.get(fi, ())))

    def returns_donating(self, fi):
        self._ensure_donation()
        return self._returns_donating.get(fi)

    def donated_sites(self, fi):
        """{(line, col): donated positions} for every call site in
        ``fi`` the analyzer can prove donating — the donation rule's
        interprocedural feed (call-site positional terms). Memoized
        per function after the fixpoint settles."""
        self._ensure_donation()
        got = self._donated_sites.get(fi)
        if got is None:
            got = self._donated_sites[fi] = self._site_indices(fi)
        return got
