"""mxlife's lifecycle model: future typestate over exception paths.

The runtime makes hard lifecycle promises the dynamic lanes can only
spot-check: serving promises zero hung futures (every admitted
``_Request``'s future resolves exactly once, on EVERY path including
the exception paths), checkpointing promises temp+fsync+rename
atomicity with unlink-on-failure, the flight recorder assumes every
entered span exits. This module is the shared substrate the three
mxlife rules (``future-lifecycle``, ``resource-release``,
``torn-state-on-raise``) consume:

* **future classes** — classes whose constructor binds
  ``self.<attr> = concurrent.futures.Future()`` (the ``_Request``
  shape). Their construction is an OWNERSHIP event; attrs the same
  class binds to ``<scope>.__enter__()`` results are its *entered
  scopes* (the serving wait/req spans), which terminal resolvers are
  expected to close.

* **a per-function typestate simulator** (:class:`_Sim`) — an
  abstract interpretation of one function body tracking owned
  objects through ``U`` (unresolved) → resolved/discharged, with
  REAL exception edges: a call site whose in-scan callee
  :meth:`~.summaries.Summaries.may_raise` forks a raised state that
  walks the enclosing try/except/finally structure (handlers catch,
  ``finally`` runs on both legs, an unhandled raise is an
  exceptional function exit). Ownership starts at a future-class
  construction, a dequeue-shaped binding (``.get()`` / ``.pop()`` /
  ``.popleft()``) or a loop variable over a parameter; it discharges
  on resolve (``set_result``/``set_exception``), on transfer
  (``append``/``put``/store-to-attr/return/closure capture/pass to
  an unknown callee) or on a pass to an in-scan callee that
  *discharges* that parameter on every path. A path reaching a
  function exit with an owned object still ``U`` is a STRAND; a
  second unconditional resolve on one path is a DOUBLE-RESOLVE.
  ``if v.future.done():`` guards and ``v is SENTINEL`` comparisons
  discharge on the appropriate branch (a done future is someone
  else's resolution; a sentinel is not a request) — conservative-
  quiet, like the rest of mxflow: a finding's path is a real path.

* **a discharge fixpoint** — ``discharges_params(fn)`` (the param
  positions a function resolves-or-transfers on every normal path)
  propagates caller-ward over the call graph with a worklist, so
  ``self._shed(req, ...)`` counts as resolving ``req`` with no
  annotation, exactly like the donation and lockset fixpoints.

Only objects that touch the future machinery somewhere in the
function (a resolve site, a pass to a discharging callee) are
reported on — a dict ``.get()`` or an ordinary loop variable never
becomes a phantom obligation.
"""
from __future__ import annotations

import ast
from collections import deque

from . import callgraph as cg
from .core import expr_text, resolve_origin

_FUTURE_ORIGINS = {"concurrent.futures.Future",
                   "concurrent.futures._base.Future"}
_DEQUEUE_METHODS = {"get", "get_nowait", "pop", "popleft"}
_TRANSFER_METHODS = {"append", "appendleft", "put", "put_nowait",
                     "add", "insert", "extend"}
RESOLVE_METHODS = ("set_result", "set_exception")

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# states
U = "U"          # owned, unresolved
R = "R"          # resolved once

# simulator blow-up guard: a function whose abstract state set grows
# past this is skipped entirely (no findings, no discharge assertions)
# rather than reasoned about half-way
_MAX_STATES = 128


def file_has_lifecycle_surface(src):
    """Cheap text gate: does this file mention the future machinery at
    all? (The rule skips the graph build on trees with no resolve
    sites — the donation rule's cheap-gate pattern.)"""
    return any(m in src.text for m in RESOLVE_METHODS)


def resolve_target(node):
    """(root var name, via_future) of a resolve call's receiver —
    ``v.set_result(...)`` -> ("v", False); ``v.future.set_result(...)``
    -> ("v", True); anything deeper/unrooted -> (None, False)."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in RESOLVE_METHODS):
        return (None, False)
    recv = f.value
    if isinstance(recv, ast.Name):
        return (recv.id, False)
    if isinstance(recv, ast.Attribute) and recv.attr == "future" \
            and isinstance(recv.value, ast.Name):
        return (recv.value.id, True)
    return (None, False)


def _done_test(test):
    """(var, positive) when ``test`` is a ``v.done()`` /
    ``v.future.done()`` probe (possibly ``not``-wrapped), else None.
    ``positive`` True means the TRUE branch is the already-resolved
    side."""
    positive = True
    while isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        positive = not positive
        test = test.operand
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Attribute) \
            and test.func.attr == "done" and not test.args:
        recv = test.func.value
        if isinstance(recv, ast.Name):
            return (recv.id, positive)
        if isinstance(recv, ast.Attribute) and recv.attr == "future" \
                and isinstance(recv.value, ast.Name):
            return (recv.value.id, positive)
    return None


def _is_test(test):
    """(var, is_branch_true) for ``v is X`` / ``v is not X`` sentinel
    comparisons — on the ``is`` side the object is a known sentinel,
    not a request."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name):
        if isinstance(test.ops[0], ast.Is):
            return (test.left.id, True)
        if isinstance(test.ops[0], ast.IsNot):
            return (test.left.id, False)
    return None


class _Outcome:
    """State sets leaving one block, by exit class."""

    __slots__ = ("normal", "returns", "raises", "breaks", "continues")

    def __init__(self):
        self.normal = set()
        self.returns = []               # (state, line)
        self.raises = []                # (state, line, why)
        self.breaks = set()
        self.continues = set()


class _SimResult:
    __slots__ = ("strands", "doubles", "discharged_params", "interest",
                 "gave_up")

    def __init__(self):
        self.strands = []     # (var, own_line, exit_line, why)
        self.doubles = []     # (var, line, first_line)
        self.discharged_params = frozenset()
        # (var, line) of every touch of the future machinery — LINE-
        # keyed so a reused loop-variable name in another loop of the
        # same function never inherits interest it did not earn
        self.interest = set()
        self.gave_up = False


class _Sim:
    """One function's typestate pass (see module docstring)."""

    def __init__(self, model, fi):
        self.model = model
        self.graph = model.graph
        self.summ = model.summ
        self.fi = fi
        self.facts = model.summ.facts_of(fi)
        self.edges = {(l, c): callee for callee, l, c
                      in model.graph.callees(fi, kinds=(cg.CALL,))}
        self.res = _SimResult()
        self.own_line = {}              # var -> ownership line
        self.first_resolve = {}         # var -> line of first resolve seen

    # -- state helpers -------------------------------------------------------
    @staticmethod
    def _set(state, var, st):
        d = dict(state)
        d[var] = st
        return tuple(sorted(d.items()))

    @staticmethod
    def _drop(state, var):
        return tuple((k, v) for k, v in state if k != var)

    @staticmethod
    def _get(state, var):
        for k, v in state:
            if k == var:
                return v
        return None

    def _guard(self, states):
        if len(states) > _MAX_STATES:
            self.res.gave_up = True
            return set(list(states)[:_MAX_STATES])
        return states

    # -- events --------------------------------------------------------------
    def _callee_discharges(self, key, call):
        """Call-arg positions (as written) this call discharges, or
        None for an unknown/dynamic callee (which discharges every
        bare-Name arg, conservative-quiet)."""
        callee = self.edges.get(key)
        if callee is None:
            return None
        d = self.model._discharges.get(callee, frozenset())
        if not d:
            return frozenset()
        shift = 1 if (callee.self_class is not None
                      and not callee.is_static
                      and isinstance(call.func, ast.Attribute)) else 0
        return frozenset(i - shift for i in d if i - shift >= 0)

    def _apply_call(self, call, state, out):
        """Apply one call's events to ``state``; exceptional fork is
        recorded into ``out.raises`` by the caller (the statement
        executor), which knows the try context structurally."""
        key = (call.lineno, call.col_offset)
        var, _viaf = resolve_target(call)
        if var is not None and self._get(state, var) is not None:
            self.res.interest.add((var, call.lineno))
            st = self._get(state, var)
            if st == U:
                state = self._set(state, var, R)
                self.first_resolve.setdefault(var, call.lineno)
            else:
                self.res.doubles.append(
                    (var, call.lineno,
                     self.first_resolve.get(var, call.lineno)))
            return state
        f = call.func
        # transfer-shaped method calls: buf.append(v), q.put(v)
        if isinstance(f, ast.Attribute) and f.attr in _TRANSFER_METHODS:
            for a in call.args:
                if isinstance(a, ast.Name) \
                        and self._get(state, a.id) is not None:
                    state = self._drop(state, a.id)
            return state
        # bare-Name args: discharge via a discharging callee (interest)
        # or via an unknown callee (ownership may transfer; no interest)
        discharges = self._callee_discharges(key, call)
        for i, a in enumerate(call.args):
            if not (isinstance(a, ast.Name)
                    and self._get(state, a.id) is not None):
                continue
            if discharges is None:
                state = self._drop(state, a.id)
            elif i in discharges:
                self.res.interest.add((a.id, call.lineno))
                state = self._drop(state, a.id)
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) \
                    and self._get(state, kw.value.id) is not None \
                    and discharges is None:
                state = self._drop(state, kw.value.id)
        return state

    def _owning_value(self, value):
        """Does binding from this expression START an ownership?
        ("ctor" for a future-class construction, "dequeue" for a
        get/pop-shaped call), else None."""
        if not isinstance(value, ast.Call):
            return None
        key = (value.lineno, value.col_offset)
        callee = self.edges.get(key)
        if callee is not None and callee.name == "__init__" \
                and callee.self_class in self.model.future_classes:
            return "ctor"
        f = value.func
        if isinstance(f, ast.Attribute) and f.attr in _DEQUEUE_METHODS:
            return "dequeue"
        return None

    def _calls_in(self, node):
        """Call nodes inside ``node``, source order, not descending
        into nested def/class bodies (their own scope)."""
        out = []
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_NODES + (ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        out.sort(key=lambda c: (c.lineno, c.col_offset))
        return out

    def _captured_names(self, defnode):
        """Names a nested def/lambda loads — an owned var captured by
        a closure escapes the analyzer's sight (discharge)."""
        names = set()
        for n in ast.walk(defnode):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                names.add(n.id)
        return names

    def _exec_simple(self, stmt, states, out):
        """Linear statement: apply each call event in source order,
        forking a raised state at each in-scan may-raise site."""
        calls = self._calls_in(stmt)
        new_states = set()
        for state in states:
            cur = {state}
            for call in calls:
                key = (call.lineno, call.col_offset)
                callee = self.edges.get(key)
                nxt = set()
                for st in cur:
                    if callee is not None and self.summ.may_raise(callee):
                        out.raises.append(
                            (st, call.lineno, ("call", callee)))
                    nxt.add(self._apply_call(call, st, out))
                cur = nxt
            new_states |= cur
        # binding effects after the value's calls ran
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t, v = stmt.targets[0], stmt.value
            owned = self._owning_value(v) if isinstance(t, ast.Name) \
                else None
            if owned is not None:
                self.own_line.setdefault(t.id, stmt.lineno)
                new_states = {self._set(s, t.id, U) for s in new_states}
            elif isinstance(v, ast.Name):
                if isinstance(t, ast.Name):
                    # alias rename: w = v moves the obligation
                    renamed = set()
                    for s in new_states:
                        st = self._get(s, v.id)
                        if st is not None:
                            s = self._set(self._drop(s, v.id), t.id, st)
                            self.own_line.setdefault(
                                t.id, self.own_line.get(v.id,
                                                        stmt.lineno))
                        renamed.add(s)
                    new_states = renamed
                elif isinstance(t, (ast.Attribute, ast.Subscript)):
                    # escape: stored beyond the frame
                    new_states = {self._drop(s, v.id)
                                  for s in new_states}
        # a nested def capturing an owned var escapes it
        if isinstance(stmt, _FUNC_NODES):
            captured = self._captured_names(stmt)
            pruned = set()
            for s in new_states:
                for name in captured:
                    if self._get(s, name) is not None:
                        s = self._drop(s, name)
                pruned.add(s)
            new_states = pruned
        return self._guard(new_states)

    # -- control flow --------------------------------------------------------
    def exec_block(self, stmts, states):
        out = _Outcome()
        cur = set(states)
        for stmt in stmts:
            if not cur:
                break
            cur = self._exec_stmt(stmt, cur, out)
        out.normal = cur
        return out

    def _merge(self, out, sub):
        out.returns.extend(sub.returns)
        out.raises.extend(sub.raises)
        out.breaks |= sub.breaks
        out.continues |= sub.continues

    def _exec_stmt(self, stmt, states, out):
        if isinstance(stmt, ast.Return):
            nxt = self._exec_simple(stmt, states, out)
            if isinstance(stmt.value, ast.Name):
                nxt = {self._drop(s, stmt.value.id) for s in nxt}
            out.returns.extend((s, stmt.lineno) for s in nxt)
            return set()
        if isinstance(stmt, ast.Raise):
            nxt = self._exec_simple(stmt, states, out)
            why = ("raise", expr_text(stmt.exc.func
                                      if isinstance(stmt.exc, ast.Call)
                                      else stmt.exc)
                   if stmt.exc is not None else "re-raise")
            out.raises.extend((s, stmt.lineno, why) for s in nxt)
            return set()
        if isinstance(stmt, ast.Break):
            out.breaks |= states
            return set()
        if isinstance(stmt, ast.Continue):
            out.continues |= states
            return set()
        if isinstance(stmt, ast.If):
            return self._exec_if(stmt, states, out)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._exec_for(stmt, states, out)
        if isinstance(stmt, ast.While):
            return self._exec_while(stmt, states, out)
        if isinstance(stmt, (ast.Try,) + ((ast.TryStar,)
                                          if hasattr(ast, "TryStar")
                                          else ())):
            return self._exec_try(stmt, states, out)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            mid = states
            for item in stmt.items:
                mid = self._exec_simple(ast.Expr(
                    value=item.context_expr), mid, out)
            sub = self.exec_block(stmt.body, mid)
            self._merge(out, sub)
            return sub.normal
        return self._exec_simple(stmt, states, out)

    def _exec_if(self, stmt, states, out):
        states = self._exec_simple(ast.Expr(value=stmt.test), states,
                                   out)
        done = _done_test(stmt.test)
        sentinel = _is_test(stmt.test)
        true_states, false_states = set(states), set(states)
        if done is not None:
            # the done side: someone already resolved it — discharge.
            # the NOT-done side: a state where WE already resolved (R)
            # is runtime-infeasible there (done() would return True) —
            # prune it, or a guarded late resolve after an earlier
            # resolve would report a phantom double
            var, positive = done
            if positive:
                true_states = {self._drop(s, var) for s in true_states}
                false_states = {s for s in false_states
                                if self._get(s, var) != R}
            else:
                false_states = {self._drop(s, var)
                                for s in false_states}
                true_states = {s for s in true_states
                               if self._get(s, var) != R}
            self.res.interest.add((var, stmt.lineno))
        if sentinel is not None:
            var, is_true = sentinel
            if is_true:
                true_states = {self._drop(s, var) for s in true_states}
            else:
                false_states = {self._drop(s, var)
                                for s in false_states}
        sub_t = self.exec_block(stmt.body, true_states)
        sub_f = self.exec_block(stmt.orelse, false_states)
        self._merge(out, sub_t)
        self._merge(out, sub_f)
        return self._guard(sub_t.normal | sub_f.normal)

    def _iter_root(self, node):
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def _exec_for(self, stmt, states, out):
        states = self._exec_simple(ast.Expr(value=stmt.iter), states,
                                   out)
        root = self._iter_root(stmt.iter)
        if root is not None:
            # iterating a tracked collection discharges the collection
            # itself — the per-element obligations take over
            states = {self._drop(s, root) for s in states}
        loopvar = stmt.target.id if isinstance(stmt.target, ast.Name) \
            else None
        body_states = states
        if loopvar is not None:
            self.own_line.setdefault(loopvar, stmt.lineno)
            body_states = {self._set(s, loopvar, U) for s in states}
        sub = self.exec_block(stmt.body, body_states)
        # one iteration's end (fall-through or continue) must have
        # discharged the element — the next iteration rebinds it
        for s in sub.normal | sub.continues:
            if loopvar is not None and self._get(s, loopvar) == U:
                self.res.strands.append(
                    (loopvar, stmt.lineno, stmt.lineno,
                     ("loop", stmt.lineno,
                      getattr(stmt, "end_lineno", stmt.lineno))))
        out.raises.extend(sub.raises)
        out.returns.extend(sub.returns)
        after = {self._drop(s, loopvar) if loopvar is not None else s
                 for s in sub.normal | sub.continues | sub.breaks}
        after |= states                  # zero iterations
        sub_else = self.exec_block(stmt.orelse, after)
        self._merge(out, sub_else)
        return self._guard(sub_else.normal)

    def _exec_while(self, stmt, states, out):
        states = self._exec_simple(ast.Expr(value=stmt.test), states,
                                   out)
        sub = self.exec_block(stmt.body, states)
        out.raises.extend(sub.raises)
        out.returns.extend(sub.returns)
        after = states | sub.normal | sub.continues | sub.breaks
        sub_else = self.exec_block(stmt.orelse, after)
        self._merge(out, sub_else)
        return self._guard(sub_else.normal)

    def _exec_try(self, stmt, states, out):
        body = self.exec_block(stmt.body, states)
        raised_states = {s for s, _l, _w in body.raises}
        escaped = []
        handler_normal = set()
        returns = list(body.returns)
        breaks = set(body.breaks)
        continues = set(body.continues)
        if stmt.handlers:
            for h in stmt.handlers:
                sub = self.exec_block(h.body, raised_states)
                handler_normal |= sub.normal
                escaped.extend(sub.raises)
                returns.extend(sub.returns)
                breaks |= sub.breaks
                continues |= sub.continues
        else:
            escaped = list(body.raises)
        sub_else = self.exec_block(stmt.orelse, body.normal)
        escaped.extend(sub_else.raises)
        normal = sub_else.normal | handler_normal
        returns.extend(sub_else.returns)
        breaks |= sub_else.breaks
        continues |= sub_else.continues
        if stmt.finalbody:
            # EVERY leg runs the finally: fall-through, the exception
            # leg (then re-raises), and the return/break/continue legs
            # (then resumes the exit) — a future resolved in a finally
            # covers a `return` inside the try too
            fin = self.exec_block(stmt.finalbody, normal)
            self._merge(out, fin)
            normal = fin.normal

            def _through_final(items, emit):
                for item in items:
                    fsub = self.exec_block(stmt.finalbody, {item[0]})
                    out.returns.extend(fsub.returns)
                    out.raises.extend(fsub.raises)
                    for s2 in fsub.normal:
                        emit(s2, item)

            new_escaped = []
            _through_final(escaped,
                          lambda s2, it: new_escaped.append(
                              (s2, it[1], it[2])))
            escaped = new_escaped
            new_returns = []
            _through_final(returns,
                          lambda s2, it: new_returns.append(
                              (s2, it[1])))
            returns = new_returns
            for legs, sink in ((breaks, "breaks"),
                               (continues, "continues")):
                passed = set()
                for s in legs:
                    fsub = self.exec_block(stmt.finalbody, {s})
                    out.returns.extend(fsub.returns)
                    out.raises.extend(fsub.raises)
                    passed |= fsub.normal
                if sink == "breaks":
                    breaks = passed
                else:
                    continues = passed
        out.raises.extend(escaped)
        out.returns.extend(returns)
        out.breaks |= breaks
        out.continues |= continues
        return self._guard(normal)

    # -- entry ---------------------------------------------------------------
    def run(self):
        params = [p for p in self.facts.params if p != "self"]
        entry = tuple(sorted((p, U) for p in params))
        out = self.exec_block(self.fi.node.body, {entry})
        res = self.res
        if res.gave_up:
            return res
        exits = [(s, line, ("return", line))
                 for s, line in out.returns]
        exits += [(s, None, ("return", None)) for s in out.normal]
        raise_exits = [(s, line, why) for s, line, why in out.raises]
        # discharged params: resolved or gone from EVERY normal exit
        # state (a param left in R was resolved — that IS the caller's
        # discharge; only a still-U param keeps the obligation there)
        still = set()
        for s, _line, _why in exits:
            for var, st in s:
                if st == U:
                    still.add(var)
        res.discharged_params = frozenset(
            i for i, p in enumerate(self.facts.params)
            if p != "self" and p not in still)
        # strands: owned-with-interest vars alive at an exit. Raise
        # exits report the raising site; normal exits the return line.
        for s, line, why in exits + raise_exits:
            for var, st in s:
                if st != U or var in self.facts.params:
                    continue
                res.strands.append(
                    (var, self.own_line.get(var, self.fi.line),
                     line if line is not None else self.fi.line, why))
        return res


class LifecycleModel:
    """Future classes + per-function typestate results over one
    Project (built once per run via ``project.lifecycle()``)."""

    def __init__(self, project, graph):
        self.project = project
        self.graph = graph
        self.summ = project.summaries()
        self.future_classes = {}        # ClassInfo -> {"attrs", "scopes"}
        self.resolve_sites = {}         # FuncInfo -> [resolve Call nodes]
        self.scope_exits = {}           # FuncInfo -> set of attr names
        self._discharges = {}           # FuncInfo -> frozenset(param idx)
        self.results = {}               # FuncInfo -> _SimResult
        self._collect()
        self._fixpoint()

    # -- collection ----------------------------------------------------------
    def _collect(self):
        for ci in self.graph.classes:
            amap = self.graph.imports_of(ci.src)
            attrs, scopes = set(), set()
            for m in ci.methods.values():
                for n in self.graph.nodes_of(m):
                    if not (isinstance(n, ast.Assign)
                            and len(n.targets) == 1):
                        continue
                    t, v = n.targets[0], n.value
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and isinstance(v, ast.Call)):
                        continue
                    origin = resolve_origin(v.func, amap)
                    if origin in _FUTURE_ORIGINS:
                        attrs.add(t.attr)
                    if isinstance(v.func, ast.Attribute) \
                            and v.func.attr == "__enter__":
                        scopes.add(t.attr)
            if attrs:
                self.future_classes[ci] = {"attrs": attrs,
                                           "scopes": scopes}
        for fi in self.graph.functions:
            sites, exits = [], set()
            for n in self.graph.nodes_of(fi):
                if not isinstance(n, ast.Call):
                    continue
                var, _viaf = resolve_target(n)
                if var is not None:
                    sites.append(n)
                f = n.func
                if isinstance(f, ast.Attribute) and f.attr == "__exit__" \
                        and isinstance(f.value, ast.Attribute):
                    exits.add(f.value.attr)
            if sites:
                self.resolve_sites[fi] = sites
            if exits:
                self.scope_exits[fi] = exits

    # -- fixpoint ------------------------------------------------------------
    def _fixpoint(self):
        candidates = set(self.resolve_sites)
        # functions constructing a future class are owners too
        ctor_inits = {self.graph._lookup_method(ci, "__init__")
                      for ci in self.future_classes}
        for fi in self.graph.functions:
            for callee, _l, _c in self.graph.callees(fi,
                                                     kinds=(cg.CALL,)):
                if callee in ctor_inits:
                    candidates.add(fi)
        pending = deque(candidates)
        queued = set(pending)
        rounds = 0
        limit = max(64, 8 * (len(candidates) + 1))
        while pending and rounds < limit:
            rounds += 1
            fi = pending.popleft()
            queued.discard(fi)
            res = _Sim(self, fi).run()
            self.results[fi] = res
            if res.discharged_params != self._discharges.get(
                    fi, frozenset()):
                self._discharges[fi] = res.discharged_params
                for caller, _l, _c in self.graph.callers(
                        fi, kinds=(cg.CALL,)):
                    candidates.add(caller)
                    if caller not in queued:
                        pending.append(caller)
                        queued.add(caller)

    # -- queries -------------------------------------------------------------
    def discharges_params(self, fi):
        return self._discharges.get(fi, frozenset())

    def span_attr_universe(self):
        out = set()
        for rec in self.future_classes.values():
            out |= rec["scopes"]
        return out

    def stats(self):
        return {
            "lifecycle_future_classes": len(self.future_classes),
            "lifecycle_resolver_functions": len(self.resolve_sites),
            "lifecycle_simulated_functions": len(self.results),
            "may_raise_functions": self.summ.may_raise_count(),
        }
