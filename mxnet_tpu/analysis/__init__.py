"""mxlint: AST-based static analysis for the runtime's own invariants.

The runtime rests on conventions no type checker knows about: every
jitted program must compile through ``executor._InstrumentedProgram``
(the program-card / recompile-diagnosis / OOM-enrichment wrapper),
lock-guarded shared state in the threaded serving/telemetry/cache
layers must be touched under its lock, hot loops must not block on
device values, donated buffers die at the call that donates them, and
the fault-site / counter / fallback-code registries must stay in sync
across five modules. Until ISSUE 8 these were enforced by two ``grep``
stanzas in ``tools/run_checks.sh`` — which an aliased
``from jax import jit`` walked straight past, and which could not see
scopes, locks or dataflow at all.

This package is the real analyzer (TVM's machine-checkable IR
invariants, arXiv:1802.04799, applied to our own host runtime):

* per-file :mod:`ast` passes plus cross-file registry passes;
* ``# mxlint: disable=<rule> -- <justification>`` suppressions (the
  justification text is REQUIRED — a bare disable is itself a finding);
* a committed baseline file for grandfathered findings
  (``tools/mxlint_baseline.json``) whose stale entries warn and are
  pruned on ``--update-baseline`` instead of erroring;
* text and JSON reports with stable exit codes (0 clean, 1 findings,
  2 usage error) — see ``tools/mxlint.py``.

Rules shipped (ids are stable; tests and suppressions key on them):

==================== ======================================================
``jit-site``         any ``jax.jit`` / ``pjit`` / ``jax.pmap`` call or
                     decorator outside the ONE marked
                     ``_InstrumentedProgram`` site, resolved through
                     import aliases
``dispatch-hook``    raw ``dispatch_hook(...)`` calls outside
                     ``executor.py`` (report via
                     ``executor.record_dispatch``)
``lock-discipline``  ``# guarded by: <lock>`` attributes/globals read or
                     written outside a ``with``-block on that lock
                     (Condition aliases count), plus no lock acquisition
                     inside a ``weakref.finalize`` callback (the PR 4
                     finalizer-deadlock class)
``host-sync``        ``.asnumpy()`` / ``.wait_to_read()`` /
                     ``np.asarray(...)`` inside functions marked
                     ``# mxlint: hot``
``donation-safety``  reuse of a Python name after it was passed at a
                     donated position of a donated-buffer program call
``registry-consistency``
                     ``faults.fire`` site strings vs ``faults.SITES``,
                     ``FusedFallback`` codes vs ``FUSED_FALLBACK_CODES``,
                     ``telemetry.counter_inc`` literals vs
                     ``telemetry.COUNTERS`` — both directions (undeclared
                     use AND unused declaration)
``lockset``          (mxflow) RacerD-style inference: a ``self.<attr>``
                     locked on some paths and bare on others, with the
                     missing ``# guarded by:`` line proposed
``trace-purity``     (mxflow) side effects reachable from a traced entry
                     point over call+ref edges
``thread-race``      (mxsync) a ``self.<attr>``/module global written
                     under one THREAD ROOT (Thread/Timer/pool-submit/
                     HTTP-handler/atexit/signal/excepthook/finalizer,
                     propagated over call+ref edges) and touched under a
                     different root with an empty lockset intersection —
                     both witness chains in the finding
``collective-discipline``
                     (mxsync) host-level cross-process collectives
                     (``_host_allgather``, ``# mxsync: collective
                     channel=<c>``-marked primitives) must be dominated
                     by a matching-channel ``CollectiveGate`` crossing,
                     and no rank/clock/fault-derived branch may make its
                     arms reach different collective sequences
==================== ======================================================

``host-sync`` and ``donation-safety`` also carry interprocedural
layers (mxflow): transitive blocking fetches with the witness chain,
and donation facts propagated through in-repo callees.
"""
from .core import (Finding, Source, Project, Baseline, Report, run,
                   iter_python_files, ALL_RULE_IDS)

__all__ = ["Finding", "Source", "Project", "Baseline", "Report", "run",
           "iter_python_files", "ALL_RULE_IDS"]
