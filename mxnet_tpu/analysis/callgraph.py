"""mxflow's project-wide call graph (the interprocedural substrate).

Everything interprocedural in mxlint — trace purity, transitive
host-sync, lockset inference, donation propagation — runs over ONE
graph built here, once per :class:`~.core.Project`:

* an entity per ``def`` (module functions, methods, and NESTED
  functions — the executor's traced closures are nested defs, so they
  must be first-class nodes, not attributes of their parent);
* name resolution through import aliases, absolute AND relative
  (``from . import telemetry`` in ``mxnet_tpu/serving.py`` binds
  ``telemetry`` to the scanned ``mxnet_tpu/telemetry.py``) — purely
  textual, no module is ever imported;
* method resolution via self-type inference: ``self.m()`` under
  ``class C`` resolves to ``C.m`` (base classes defined in scanned
  files are searched, bounded); ``x = ClassName(...)`` followed by
  ``x.m()`` in the same function resolves through the local instance
  type;
* two edge kinds: ``call`` (the expression is invoked here) and
  ``ref`` (a known function is passed as a VALUE argument —
  ``jax.vjp(f, ...)``, ``jax.checkpoint(f)`` — the callee runs under
  whoever receives it, which for tracing entry points means: during
  the trace). Trace-purity traverses both; transitive host-sync
  traverses only ``call`` edges (a callback handed to the resolver
  pool legitimately blocks on its own thread);
* BOUNDED dynamic calls: a call through a parameter, a dict lookup
  (``plan["fn"](...)``) or an unresolvable attribute is recorded as a
  dynamic call on the caller and never traversed — the explicit
  comment grammar (``# mxlint: donates``, justified disables) remains
  the escape hatch, and ``stats()`` reports how much of the graph is
  dark;
* Tarjan SCCs (iterative — no recursion limit risk) so bottom-up
  summary passes and the tests can reason about recursion cycles.

The graph is deliberately unsound-by-choice in the conservative
direction each rule needs: edges only exist when resolution is
certain, so a finding's chain is always a real call path in the
source.
"""
from __future__ import annotations

import ast

from .core import resolve_origin

# edge kinds
CALL = "call"
REF = "ref"

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_TRY_NODES = (ast.Try,) + ((ast.TryStar,)
                           if hasattr(ast, "TryStar") else ())


class FuncInfo:
    """One function entity. Identity is the object itself; ``key``
    (display path, qualname) is the stable cross-run name used in
    reports and caches."""

    __slots__ = ("src", "node", "qualname", "self_class", "line",
                 "is_static")

    def __init__(self, src, node, qualname, self_class):
        self.src = src
        self.node = node
        self.qualname = qualname
        self.self_class = self_class        # ClassInfo or None
        self.line = node.lineno
        # @staticmethod takes no bound receiver: donation positions
        # need no self-shift at attribute call sites
        self.is_static = any(
            isinstance(d, ast.Name) and d.id == "staticmethod"
            for d in node.decorator_list)

    @property
    def key(self):
        return (self.src.display, self.qualname)

    @property
    def name(self):
        return self.node.name

    def label(self):
        return "%s:%s" % (self.src.display, self.qualname)

    def __repr__(self):
        return "FuncInfo(%s)" % self.label()


class ClassInfo:
    __slots__ = ("src", "node", "qualname", "methods", "base_exprs")

    def __init__(self, src, node, qualname):
        self.src = src
        self.node = node
        self.qualname = qualname
        self.methods = {}               # name -> FuncInfo
        self.base_exprs = list(node.bases)

    def __repr__(self):
        return "ClassInfo(%s:%s)" % (self.src.display, self.qualname)


def module_name_of(display):
    """Dotted module name a repo-relative path would import as
    (``mxnet_tpu/module/base_module.py`` -> ``mxnet_tpu.module.
    base_module``; a package ``__init__.py`` names the package)."""
    p = display
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _import_map(src):
    """{local name: dotted origin} including RELATIVE imports (which
    :meth:`Source.import_aliases` deliberately skips — jit-site wants
    only absolute jax origins, the call graph wants everything).
    Memoized on the Source: the graph builder and the effect-summary
    extractor both ask, and the walk is a full-tree pass."""
    got = getattr(src, "_rich_aliases", None)
    if got is not None:
        return got
    out = dict(src.import_aliases())
    module = module_name_of(src.display)
    # the containing package: an __init__.py IS its package (its
    # module name already dropped the '__init__' segment), so level=1
    # resolves against the module name itself, not its parent —
    # otherwise `from . import util` inside pkg/__init__.py binds
    # 'util' instead of 'pkg.util' and every edge out of a package
    # __init__ silently vanishes
    if src.display.endswith("__init__.py"):
        pkg_parts = module.split(".") if module else []
    else:
        pkg_parts = module.split(".")[:-1] if module else []
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.ImportFrom) and node.level > 0):
            continue
        # level=1: the containing package; level=2: its parent, ...
        up = node.level - 1
        base = pkg_parts[:len(pkg_parts) - up] if up else list(pkg_parts)
        if node.module:
            base = base + node.module.split(".")
        prefix = ".".join(base)
        for a in node.names:
            if a.name == "*":
                continue
            origin = "%s.%s" % (prefix, a.name) if prefix else a.name
            out[a.asname or a.name] = origin
    src._rich_aliases = out
    return out


class _Collector(ast.NodeVisitor):
    """One pass per file: every function/class entity with its
    lexical scope chain."""

    def __init__(self, graph, src):
        self.graph = graph
        self.src = src
        self.scope = []                 # mix of FuncInfo / ClassInfo

    def _qual(self, name):
        if self.scope:
            return "%s.%s" % (self.scope[-1].qualname, name)
        return name

    def _self_class(self):
        # the class a `self` in this position would refer to: nearest
        # enclosing ClassInfo reached only through functions (a class
        # nested inside a method starts a fresh `self`)
        for s in reversed(self.scope):
            if isinstance(s, ClassInfo):
                return s
            if not isinstance(s, FuncInfo):
                return None
        return None

    def visit_ClassDef(self, node):
        ci = ClassInfo(self.src, node, self._qual(node.name))
        self.graph._add_class(ci)
        self.scope.append(ci)
        for stmt in node.body:
            self.visit(stmt)
        self.scope.pop()

    def _visit_func(self, node):
        owner = self.scope[-1] if self.scope else None
        self_class = self._self_class()
        fi = FuncInfo(self.src, node, self._qual(node.name), self_class)
        self.graph._add_func(fi, enclosing=[s for s in self.scope
                                            if isinstance(s, FuncInfo)])
        if isinstance(owner, ClassInfo):
            owner.methods.setdefault(node.name, fi)
        self.scope.append(fi)
        for stmt in node.body:
            self.visit(stmt)
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


class CallGraph:
    """Entities + resolved edges over one Project. Build with
    :func:`build` (or ``project.callgraph()``)."""

    def __init__(self):
        self.functions = []             # all FuncInfo, file order
        self.classes = []
        self._by_key = {}               # (display, qualname) -> FuncInfo
        self._module_index = {}         # dotted module name -> src
        self._module_funcs = {}         # src -> {name: FuncInfo}
        self._module_classes = {}       # src -> {name: ClassInfo}
        self._nested = {}               # FuncInfo -> {name: FuncInfo}
        self._enclosing = {}            # FuncInfo -> tuple of FuncInfo
        self._node_func = {}            # (src, id(def node)) -> FuncInfo
        self._imports = {}              # src -> import map
        self._edges = {}                # FuncInfo -> [(callee, line, col, kind)]
        self._redges = {}               # FuncInfo -> [(caller, line, col, kind)]
        self.dynamic_calls = {}         # FuncInfo -> count
        self._n_edges = 0
        self._sccs = None
        self._locals = {}               # FuncInfo -> frozenset of names
        self._by_src = None             # src -> [FuncInfo]
        self._scope_nodes = {}          # FuncInfo -> tuple of scope nodes
        self._try_maps = {}             # FuncInfo -> {id(node): ctx}

    # -- construction -------------------------------------------------------
    def _add_class(self, ci):
        self.classes.append(ci)
        if ci.qualname.count(".") == 0:          # module-level classes only
            self._module_classes.setdefault(ci.src, {})[ci.qualname] = ci

    def _add_func(self, fi, enclosing):
        self.functions.append(fi)
        self._by_key.setdefault(fi.key, fi)
        self._node_func[(fi.src, id(fi.node))] = fi
        self._enclosing[fi] = tuple(enclosing)
        if enclosing:
            self._nested.setdefault(enclosing[-1], {})[fi.name] = fi
        elif "." not in fi.qualname:             # plain module function
            self._module_funcs.setdefault(fi.src, {})[fi.name] = fi

    def _add_edge(self, caller, callee, node, kind):
        self._edges.setdefault(caller, []).append(
            (callee, node.lineno, node.col_offset, kind))
        self._redges.setdefault(callee, []).append(
            (caller, node.lineno, node.col_offset, kind))
        self._n_edges += 1

    # -- lookups ------------------------------------------------------------
    def imports_of(self, src):
        got = self._imports.get(src)
        if got is None:
            got = self._imports[src] = _import_map(src)
        return got

    def func_for_node(self, src, node):
        """FuncInfo of a def node seen by a rule (or None)."""
        return self._node_func.get((src, id(node)))

    def nodes_of(self, fi):
        """The function's same-scope AST nodes, materialized once —
        the mxsync models each need several passes over every
        function, and re-walking the tree per pass dominated their
        build time."""
        got = self._scope_nodes.get(fi)
        if got is None:
            got = self._scope_nodes[fi] = tuple(
                _walk_same_scope(fi.node))
        return got

    def try_map_of(self, fi):
        """{id(same-scope node): tuple of (Try node, region)} —
        outermost-first exception context of every node in the
        function's own scope. ``region`` is ``"try"`` (guarded by the
        Try's handlers, if any), ``"handler"``, ``"orelse"`` or
        ``"final"`` (all three propagate past their own Try). Nested
        def/class bodies are their own scope and are not descended
        into. Materialized once per function (the mxlife rules each
        ask several times per function)."""
        got = self._try_maps.get(fi)
        if got is None:
            got = self._try_maps[fi] = _build_try_map(fi.node)
        return got

    def functions_of(self, src):
        """Every FuncInfo defined in one source file."""
        if self._by_src is None:
            self._by_src = {}
            for fi in self.functions:
                self._by_src.setdefault(fi.src, []).append(fi)
        return self._by_src.get(src, ())

    def callees(self, fi, kinds=(CALL,)):
        return [(c, ln, col) for c, ln, col, k in self._edges.get(fi, ())
                if k in kinds]

    def callers(self, fi, kinds=(CALL,)):
        return [(c, ln, col) for c, ln, col, k in self._redges.get(fi, ())
                if k in kinds]

    def resolve_dotted(self, origin):
        """('func', FuncInfo) | ('class', ClassInfo) | None for a dotted
        origin like ``mxnet_tpu.telemetry.counter_inc`` — matched
        against the LONGEST scanned-module prefix."""
        parts = origin.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            src = self._module_index.get(".".join(parts[:cut]))
            if src is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                fi = self._module_funcs.get(src, {}).get(rest[0])
                if fi is not None:
                    return ("func", fi)
                ci = self._module_classes.get(src, {}).get(rest[0])
                if ci is not None:
                    return ("class", ci)
            elif len(rest) == 2:
                ci = self._module_classes.get(src, {}).get(rest[0])
                if ci is not None:
                    m = self._lookup_method(ci, rest[1])
                    if m is not None:
                        return ("func", m)
            return None
        return None

    def resolve_name(self, src, scope, name):
        """What bare ``name`` means inside ``scope`` (a FuncInfo, or
        None for module level): ('func', fi) | ('class', ci) | None.
        Lexical nested defs shadow module functions shadow imports."""
        if scope is not None:
            chain = (self._enclosing.get(scope, ()) + (scope,))
            for s in reversed(chain):
                fi = self._nested.get(s, {}).get(name)
                if fi is not None:
                    return ("func", fi)
            # any OTHER local binding (param, assignment, loop var)
            # shadows module scope with a value the graph cannot see —
            # resolving past it would fabricate an edge to the
            # shadowed module function, breaking the 'every chain is a
            # real call path' guarantee; None here lands in the
            # caller's local-name-means-dynamic fallthrough
            if name in self._locals_of(scope):
                return None
        fi = self._module_funcs.get(src, {}).get(name)
        if fi is not None:
            return ("func", fi)
        ci = self._module_classes.get(src, {}).get(name)
        if ci is not None:
            return ("class", ci)
        origin = self.imports_of(src).get(name)
        if origin and origin != name:
            return self.resolve_dotted(origin)
        return None

    def _lookup_method(self, ci, name, _depth=0):
        """Method lookup through scanned base classes (bounded)."""
        m = ci.methods.get(name)
        if m is not None or _depth > 8:
            return m
        for base in ci.base_exprs:
            target = None
            if isinstance(base, ast.Name):
                target = self.resolve_name(ci.src, None, base.id)
            elif isinstance(base, ast.Attribute):
                origin = self._resolve_attr_origin(ci.src, base)
                if origin:
                    target = self.resolve_dotted(origin)
            if target and target[0] == "class":
                m = self._lookup_method(target[1], name, _depth + 1)
                if m is not None:
                    return m
        return None

    def _resolve_attr_origin(self, src, node):
        """Textual dotted origin of an Attribute chain under the
        file's (absolute + relative) import map — routed through the
        ONE shared resolver in core."""
        return resolve_origin(node, self.imports_of(src))

    def _locals_of(self, fi):
        """Names bound locally in a function (params + stores +
        nested defs + enclosing-function locals), for the
        call-through-a-local-is-dynamic distinction."""
        if fi is None:
            return frozenset()
        got = self._locals.get(fi)
        if got is not None:
            return got
        names = set()
        for scope in self._enclosing.get(fi, ()) + (fi,):
            for n in _walk_same_scope(scope.node):
                if isinstance(n, ast.Name) \
                        and isinstance(n.ctx, (ast.Store, ast.Del)):
                    names.add(n.id)
                elif isinstance(n, ast.arg):
                    names.add(n.arg)
                elif isinstance(n, (ast.Global, ast.Nonlocal)):
                    names.difference_update(n.names)
                elif isinstance(n, _FUNC_NODES):
                    names.add(n.name)
        got = self._locals[fi] = frozenset(names)
        return got

    # -- edge extraction ----------------------------------------------------
    def _local_instance_types(self, src, fi):
        """{var name: ClassInfo} from direct ``x = ClassName(...)``
        assignments in the function body (flow-insensitive; last
        binding wins — enough for the constructor-then-use idiom)."""
        out = {}
        for n in _walk_same_scope(fi.node):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)):
                continue
            target = None
            f = n.value.func
            if isinstance(f, ast.Name):
                target = self.resolve_name(src, fi, f.id)
            elif isinstance(f, ast.Attribute):
                origin = self._resolve_attr_origin(src, f)
                if origin:
                    target = self.resolve_dotted(origin)
            if target and target[0] == "class":
                out[n.targets[0].id] = target[1]
        return out

    def _resolve_call_target(self, src, fi, func_expr, var_types):
        """FuncInfo a call expression lands on, or the string
        'dynamic' (plausibly in-project, unresolvable) or None
        (external/builtin)."""
        if isinstance(func_expr, ast.Name):
            got = self.resolve_name(src, fi, func_expr.id)
            if got is None:
                # a bare name that is a known local/param: dynamic; an
                # unknown global (builtin, star import): external
                return "dynamic" if func_expr.id in self._locals_of(fi) \
                    else None
            if got[0] == "func":
                return got[1]
            # constructor call -> __init__ when scanned
            init = self._lookup_method(got[1], "__init__")
            return init
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and fi is not None \
                        and fi.self_class is not None:
                    m = self._lookup_method(fi.self_class, func_expr.attr)
                    return m if m is not None else "dynamic"
                ci = var_types.get(base.id)
                if ci is not None:
                    m = self._lookup_method(ci, func_expr.attr)
                    return m if m is not None else "dynamic"
            origin = self._resolve_attr_origin(src, func_expr)
            if origin:
                got = self.resolve_dotted(origin)
                if got is not None:
                    if got[0] == "func":
                        return got[1]
                    init = self._lookup_method(got[1], "__init__")
                    return init               # constructor (or external)
                # rooted at an import that is outside the scan: external
                root = func_expr
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) \
                        and root.id in self.imports_of(src):
                    return None
            # obj.method() on an untyped receiver: could be anywhere
            # in-project — dynamic
            return "dynamic"
        # plan["fn"](...), (lambda ...)(...), chained calls: dynamic
        return "dynamic"

    def _extract_edges(self, fi):
        src = fi.src
        var_types = None
        for n in _walk_same_scope(fi.node):
            if not isinstance(n, ast.Call):
                continue
            if var_types is None:
                var_types = self._local_instance_types(src, fi)
            target = self._resolve_call_target(src, fi, n.func, var_types)
            if isinstance(target, FuncInfo):
                self._add_edge(fi, target, n, CALL)
            elif target == "dynamic":
                self.dynamic_calls[fi] = self.dynamic_calls.get(fi, 0) + 1
            # function-valued ARGUMENTS: a known function passed as a
            # value (jax.vjp(f), jax.checkpoint(f), partial(f, ...))
            # runs under the receiver — a ref edge. Bound methods
            # passed as values (jax.jit(self._kernel)) resolve through
            # the same self-type machinery as self.m() call edges.
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                if isinstance(arg, ast.Name):
                    got = self.resolve_name(src, fi, arg.id)
                    if got is not None and got[0] == "func":
                        self._add_edge(fi, got[1], n, REF)
                elif isinstance(arg, ast.Attribute) \
                        and isinstance(arg.value, ast.Name) \
                        and arg.value.id in ("self", "cls") \
                        and fi is not None \
                        and fi.self_class is not None:
                    m = self._lookup_method(fi.self_class, arg.attr)
                    if m is not None:
                        self._add_edge(fi, m, n, REF)

    # -- SCCs (Tarjan, iterative) -------------------------------------------
    def sccs(self, kinds=(CALL,)):
        """List of SCCs (each a list of FuncInfo) in reverse
        topological order (callees before callers) over the given edge
        kinds."""
        if self._sccs is not None and kinds == (CALL,):
            return self._sccs
        index = {}
        low = {}
        on_stack = set()
        stack = []
        out = []
        counter = [0]

        for root in self.functions:
            if root in index:
                continue
            work = [(root, 0)]
            while work:
                node, pi = work.pop()
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                succs = self.callees(node, kinds)
                for i in range(pi, len(succs)):
                    s = succs[i][0]
                    if s not in index:
                        work.append((node, i + 1))
                        work.append((s, 0))
                        recurse = True
                        break
                    if s in on_stack:
                        low[node] = min(low[node], index[s])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w is node:
                            break
                    out.append(comp)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        if kinds == (CALL,):
            self._sccs = out
        return out

    def stats(self):
        sccs = self.sccs()
        cyclic = [c for c in sccs if len(c) > 1]
        return {
            "functions": len(self.functions),
            "classes": len(self.classes),
            "call_edges": sum(len([e for e in v if e[3] == CALL])
                              for v in self._edges.values()),
            "ref_edges": sum(len([e for e in v if e[3] == REF])
                             for v in self._edges.values()),
            "dynamic_calls": sum(self.dynamic_calls.values()),
            "sccs": len(sccs),
            "cyclic_sccs": len(cyclic),
            "largest_scc": max((len(c) for c in sccs), default=0),
        }


def _walk_same_scope(node):
    """ast.walk from a def node, not descending into NESTED def/class
    bodies (those are their own entities) but visiting decorator lists
    and default expressions of nested defs (they evaluate here). The
    ROOT def's own decorators, defaults, return annotation and
    parameter annotations are NOT visited — they evaluate at def time
    in the ENCLOSING scope, so a decorator stacked above ``@jax.jit``
    (or a ``make_spec()`` call in a param annotation) must not become
    a call edge of the traced function. Its ``ast.arg`` nodes ARE
    yielded (locals collection needs the params) but their children
    are not walked."""
    if isinstance(node, _FUNC_NODES):
        stack = list(node.body)
        a = node.args
        for arg in (list(getattr(a, "posonlyargs", [])) + list(a.args)
                    + list(a.kwonlyargs)
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            yield arg
        yield node
    else:
        stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
            yield n                      # the def itself binds a name here
            for dec in n.decorator_list:
                stack.append(dec)
            if isinstance(n, _FUNC_NODES):
                stack.extend(n.args.defaults)
                stack.extend(d for d in n.args.kw_defaults
                             if d is not None)
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _build_try_map(func_node):
    """See :meth:`CallGraph.try_map_of`."""
    out = {}

    def visit(n, ctx):
        out[id(n)] = ctx
        if isinstance(n, _TRY_NODES):
            for s in n.body:
                visit(s, ctx + ((n, "try"),))
            for h in n.handlers:
                out[id(h)] = ctx
                for s in h.body:
                    visit(s, ctx + ((n, "handler"),))
            for s in n.orelse:
                visit(s, ctx + ((n, "orelse"),))
            for s in n.finalbody:
                visit(s, ctx + ((n, "final"),))
            return
        if isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
            return                      # nested scope: its own map
        for child in ast.iter_child_nodes(n):
            visit(child, ctx)

    for stmt in func_node.body:
        visit(stmt, ())
    return out


def build(project):
    """Build the CallGraph for every parsed source in a Project."""
    g = CallGraph()
    for src in project.sources:
        mod = module_name_of(src.display)
        if mod:
            g._module_index.setdefault(mod, src)
        _Collector(g, src).visit(src.tree)
    for fi in g.functions:
        g._extract_edges(fi)
    return g
