"""mxsync's SPMD collective model: site index, gates, divergence.

A multi-process SPMD runtime dies two ways that no unit test shows: a
cross-process collective entered while a peer is already dead (cluster
hang — PR 11's ``CollectiveGate`` exists precisely to turn that into
``DeadWorkerError``), and a rank- or time-dependent branch that makes
one process run a DIFFERENT collective sequence than its peers (one
rank skips a psum; everyone else blocks in it forever). This module
indexes the collective surface statically so the
``collective-discipline`` rule can police both:

* **collective sites** — calls to ``KVStore._host_allgather`` (channel
  ``kv``), calls to functions whose ``def`` line carries a
  ``# mxsync: collective channel=<c>`` marker (the declarative index:
  ``spmd.broadcast_from_zero`` is marked ``kv``; a call-line marker
  overrides per site), and ``jax.lax`` device collectives
  (``psum``/``all_gather``/... — channel ``step``). Host-level sites
  (the first two) participate in gate-coverage checking; ``lax.*``
  sites live inside traced programs whose *dispatch* is what the gate
  protects, so they feed only the divergence/sequence checks;
* **gate crossings** — ``<gate>.arrive_and_wait()`` where the receiver
  resolves to a ``CollectiveGate(...)`` construction (local binding,
  ``self.<attr>``, a gate-returning method, or a direct chained call),
  with the channel read off the construction's ``channel=`` literal
  (default ``step``, matching the class);
* **entry-gated channels** — the meet, over every resolved call site,
  of the channels a function's callers have crossed before the call
  (lexically-earlier crossing in the caller, or the caller's own entry
  set): ``_assert_push_discipline`` is entry-gated ``kv`` because its
  only caller crosses the kv gate first. A function with a ref-edge
  caller or no callers starts ungated (anyone may reach it bare);
* **reachable-collective summaries** — per function, every collective
  label reachable over call edges; the divergence check compares the
  two arms of a rank/clock/fault-derived branch (plus the fallthrough
  suffix for arms that return/raise) and flags arms whose reachable
  collective sets differ.

Lexical position (line order within one function) stands in for
dominance — the runtime's gate-then-exchange code is straight-line —
and every reported chain is a real call path (dynamic calls are never
traversed), matching mxflow's conservative posture.
"""
from __future__ import annotations

import ast

from . import callgraph as cg
from .core import expr_text, resolve_origin
from .summaries import _CLOCK_ORIGINS, _is_rng_origin

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

ANY_CHANNEL = "<any>"

# jax.lax device collectives: inside a compiled program, protected by
# gating the DISPATCH (invisible statically) — indexed for sequence/
# divergence checks only, never for gate coverage
_LAX_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather", "jax.lax.psum_scatter", "jax.lax.all_to_all",
    "jax.lax.ppermute", "jax.lax.pshuffle",
}

# names whose value differs per process: a branch on one of these can
# desynchronise the collective sequence across ranks
_TAINT_NAMES = {"rank", "process_id", "process_index"}
_TAINT_CALL_BASENAMES = {"process_index", "getpid", "gethostname"}


class Crossing:
    __slots__ = ("line", "col", "channel")

    def __init__(self, line, col, channel):
        self.line = line
        self.col = col
        self.channel = channel          # None = unresolved gate: wildcard


class Site:
    __slots__ = ("line", "col", "channel", "kind", "host")

    def __init__(self, line, col, channel, kind, host):
        self.line = line
        self.col = col
        self.channel = channel
        self.kind = kind                # "host_allgather"/"psum"/func name
        self.host = host                # True: gate-coverage checked

    def label(self):
        return "%s[%s]" % (self.kind, self.channel)


def _gate_channel(call):
    """The ``channel=`` literal of a CollectiveGate construction
    (default "step", the class default); None for a non-literal."""
    for kw in call.keywords:
        if kw.arg == "channel":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
            return None
    return "step"


def _is_gate_ctor(call):
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return name == "CollectiveGate"


def _terminates(stmts):
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Break,
                              ast.Continue)) for s in stmts)


class CollectiveModel:
    """Sites, crossings, entry-gated channels and reachable-collective
    summaries over one Project's call graph."""

    def __init__(self, project, graph):
        self.project = project
        self.graph = graph
        self._gate_attrs = {}       # (ClassInfo, attr) -> channel
        self._gate_methods = {}     # (ClassInfo, name) -> channel
        self._fn = {}               # FuncInfo -> (crossings, sites)
        self._labels = {}           # FuncInfo -> {(l,c): set(labels)}
        self._edges = {}            # FuncInfo -> {(l,c): callee}
        self._alias_edges = {}      # FuncInfo -> {(l,c): callee} via local
        self._reach = {}            # FuncInfo -> frozenset(labels)
        self._entry = {}            # FuncInfo -> frozenset(channels)
        self._index_gates()
        for fi in graph.functions:
            self._scan_function(fi)
        self._fix_reach()
        self._fix_entry()

    # -- gate constructions --------------------------------------------------
    def _index_gates(self):
        for fi in self.graph.functions:
            if fi.self_class is None:
                continue
            nodes = self.graph.nodes_of(fi)
            for n in nodes:
                if not (isinstance(n, ast.Call) and _is_gate_ctor(n)):
                    continue
                # a construction anywhere in a method makes the method
                # gate-returning (the `self._collective_gate()` idiom)
                self._gate_methods[(fi.self_class, fi.name)] = \
                    _gate_channel(n)
            # `self.X = CollectiveGate(...)` binds the attribute
            for n in nodes:
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.value, ast.Call) \
                        and _is_gate_ctor(n.value):
                    t = n.targets[0]
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self._gate_attrs[(fi.self_class, t.attr)] = \
                            _gate_channel(n.value)

    def _crossing_channel(self, fi, call, local_gates):
        """Channel of an ``X.arrive_and_wait()`` crossing, or None
        (unresolved gate = wildcard crossing)."""
        recv = call.func.value
        ci = fi.self_class if fi is not None else None
        if isinstance(recv, ast.Name):
            if recv.id in local_gates:
                return local_gates[recv.id]
        elif isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and ci is not None:
            got = self._gate_attrs.get((ci, recv.attr))
            if got is not None:
                return got
        elif isinstance(recv, ast.Call):
            if _is_gate_ctor(recv):
                return _gate_channel(recv)
            rf = recv.func
            if isinstance(rf, ast.Attribute) \
                    and isinstance(rf.value, ast.Name) \
                    and rf.value.id == "self" and ci is not None:
                got = self._gate_methods.get((ci, rf.attr))
                if got is not None:
                    return got
        return None

    # -- per-function scan ---------------------------------------------------
    def _resolved_callee(self, fi, call, edge_map):
        key = (call.lineno, call.col_offset)
        return edge_map.get(key) or self._alias_edges.get(fi, {}).get(key)

    def _scan_function(self, fi):
        src = fi.src
        graph = self.graph
        amap = graph.imports_of(src)
        edge_map = {(l, c): callee
                    for callee, l, c in graph.callees(fi)}
        self._edges[fi] = edge_map

        nodes = self.graph.nodes_of(fi)
        # flow-insensitive local bindings: gates and function aliases
        local_gates = {}
        local_fns = {}
        for n in nodes:
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)):
                continue
            name = n.targets[0].id
            v = n.value
            if isinstance(v, ast.Call) and _is_gate_ctor(v):
                local_gates[name] = _gate_channel(v)
            elif isinstance(v, ast.Call) and not _is_gate_ctor(v):
                rf = v.func
                if isinstance(rf, ast.Attribute) \
                        and isinstance(rf.value, ast.Name) \
                        and rf.value.id == "self" \
                        and fi.self_class is not None:
                    got = self._gate_methods.get(
                        (fi.self_class, rf.attr))
                    if got is not None:
                        local_gates[name] = got
            elif isinstance(v, (ast.Name, ast.Attribute)):
                # `broadcast = broadcast_from_zero`: calls through the
                # local name are calls to the bound function
                target = None
                if isinstance(v, ast.Name):
                    got = graph.resolve_name(src, fi, v.id)
                    if got is not None and got[0] == "func":
                        target = got[1]
                else:
                    origin = resolve_origin(v, amap)
                    if origin:
                        got = graph.resolve_dotted(origin)
                        if got is not None and got[0] == "func":
                            target = got[1]
                if target is not None:
                    local_fns[name] = target

        crossings, sites = [], []
        alias_edges = {}
        for n in nodes:
            if not isinstance(n, ast.Call):
                continue
            key = (n.lineno, n.col_offset)
            f = n.func
            if isinstance(f, ast.Attribute) \
                    and f.attr == "arrive_and_wait":
                crossings.append(Crossing(
                    n.lineno, n.col_offset,
                    self._crossing_channel(fi, n, local_gates)))
                continue
            # calls through a local function alias become resolvable
            if isinstance(f, ast.Name) and f.id in local_fns \
                    and key not in edge_map:
                alias_edges[key] = local_fns[f.id]

            site = self._classify_site(fi, n, amap, edge_map,
                                       alias_edges)
            if site is not None:
                sites.append(site)
        if alias_edges:
            self._alias_edges[fi] = alias_edges
        self._fn[fi] = (crossings, sites)

        labels = {}
        for c in crossings:
            labels.setdefault((c.line, c.col), set()).add(
                "gate[%s]" % (c.channel or "?"))
        for s in sites:
            labels.setdefault((s.line, s.col), set()).add(s.label())
        self._labels[fi] = labels

    def _classify_site(self, fi, call, amap, edge_map, alias_edges):
        src = fi.src
        key = (call.lineno, call.col_offset)
        f = call.func
        # explicit call-line marker wins (channel override / opaque
        # dynamic collective)
        mark = src.collective_marks.get(call.lineno)
        callee = edge_map.get(key) or alias_edges.get(key)
        def_mark = None
        if callee is not None:
            def_mark = callee.src.collective_marks.get(callee.node.lineno)
        if mark is not None or def_mark is not None:
            name = callee.name if callee is not None else (
                f.attr if isinstance(f, ast.Attribute) else
                f.id if isinstance(f, ast.Name) else "<dynamic>")
            return Site(call.lineno, call.col_offset,
                        mark or def_mark, name, host=True)
        # the live-membership host exchange: any _host_allgather call
        if (isinstance(f, ast.Attribute)
                and f.attr == "_host_allgather") \
                or (callee is not None
                    and callee.name == "_host_allgather"):
            return Site(call.lineno, call.col_offset, "kv",
                        "host_allgather", host=True)
        origin = resolve_origin(f, amap) \
            if isinstance(f, (ast.Name, ast.Attribute)) else None
        if origin in _LAX_COLLECTIVES:
            return Site(call.lineno, call.col_offset, "step",
                        origin.rsplit(".", 1)[1], host=False)
        return None

    # -- fixpoints -----------------------------------------------------------
    def _fix_reach(self):
        graph = self.graph
        reach = {fi: set().union(*self._labels.get(fi, {}).values())
                 if self._labels.get(fi) else set()
                 for fi in graph.functions}
        from collections import deque
        pending = deque(fi for fi in graph.functions if reach[fi])
        queued = set(pending)
        while pending:
            fi = pending.popleft()
            queued.discard(fi)
            for caller, _l, _c in graph.callers(fi):
                if not reach[fi] - reach[caller]:
                    continue
                reach[caller] |= reach[fi]
                if caller not in queued:
                    pending.append(caller)
                    queued.add(caller)
            # alias edges are callers too (invisible to graph.callers)
        # fold alias edges with a bounded extra sweep: alias calls are
        # rare (one in-tree), so a simple repeated pass converges fast
        for _round in range(4):
            changed = False
            for fi, amap_edges in self._alias_edges.items():
                for callee in amap_edges.values():
                    add = reach.get(callee, set()) - reach[fi]
                    if add:
                        reach[fi] |= add
                        changed = True
            if not changed:
                break
        self._reach = {fi: frozenset(v) for fi, v in reach.items()}

    def _gated_at(self, fi, line):
        """Channels guaranteed crossed before ``line`` in ``fi``."""
        out = set(self._entry.get(fi, ()))
        for c in self._fn.get(fi, ((), ()))[0]:
            if c.line < line:
                out.add(c.channel if c.channel is not None
                        else ANY_CHANNEL)
        return out

    def _fix_entry(self):
        graph = self.graph
        # only functions from which a HOST-level site is reachable
        # matter for gate coverage; bound the fixpoint to them
        relevant = set()
        from collections import deque
        seeds = [fi for fi, (_c, sites) in self._fn.items()
                 if any(s.host for s in sites)]
        queue = deque(seeds)
        relevant.update(seeds)
        while queue:
            fi = queue.popleft()
            for caller, _l, _c in graph.callers(fi):
                if caller not in relevant:
                    relevant.add(caller)
                    queue.append(caller)

        universe = frozenset(
            [ANY_CHANNEL]
            + [s.channel for _c, ss in self._fn.values() for s in ss]
            + [c.channel for cs, _s in self._fn.values() for c in cs
               if c.channel is not None])

        def eligible(fi):
            return bool(graph.callers(fi)) \
                and not graph.callers(fi, kinds=(cg.REF,))

        entry = {fi: (universe if eligible(fi) else frozenset())
                 for fi in relevant}
        self._entry = entry
        for _round in range(len(relevant) + 2):
            changed = False
            for fi in relevant:
                if not eligible(fi):
                    continue
                new = None
                for caller, line, _col in graph.callers(fi):
                    got = frozenset(self._gated_at(caller, line)) \
                        if caller in relevant \
                        else frozenset(
                            c.channel if c.channel is not None
                            else ANY_CHANNEL
                            for c in self._fn.get(caller, ((), ()))[0]
                            if c.line < line)
                    new = got if new is None else (new & got)
                if new is None:
                    new = frozenset()
                if new != entry[fi]:
                    entry[fi] = new
                    changed = True
            if not changed:
                break

    # -- queries for the rule ------------------------------------------------
    def coverage(self):
        """[(fi, site, prior_channels)] for every HOST-level site NOT
        covered by a matching-channel (or wildcard) crossing:
        ``prior_channels`` is what IS crossed on the path — non-empty
        means a channel mismatch, empty means fully ungated."""
        out = []
        for fi, (_crossings, sites) in sorted(
                self._fn.items(), key=lambda kv: (kv[0].src.display,
                                                  kv[0].line)):
            for s in sites:
                if not s.host:
                    continue
                prior = self._gated_at(fi, s.line)
                if s.channel in prior or ANY_CHANNEL in prior:
                    continue
                out.append((fi, s, frozenset(prior)))
        return out

    def ungated_chain(self, fi, channel):
        """One real call chain from an ungated caller down to ``fi``:
        ``[(caller FuncInfo, call line), ...]`` outermost first. Empty
        when ``fi`` itself is the exposed entry."""
        graph = self.graph
        hops = []
        cur = fi
        seen = {fi}
        for _ in range(12):
            nxt = None
            for caller, line, _col in graph.callers(cur):
                if caller in seen:
                    continue
                gated = self._gated_at(caller, line)
                if channel not in gated and ANY_CHANNEL not in gated:
                    nxt = (caller, line)
                    break
            if nxt is None:
                break
            hops.append(nxt)
            seen.add(nxt[0])
            cur = nxt[0]
        hops.reverse()
        return hops

    def reach(self, fi):
        return self._reach.get(fi, frozenset())

    # -- divergence ----------------------------------------------------------
    def _taint_locals(self, fi, amap):
        tainted = {}
        for _round in range(2):
            for n in self.graph.nodes_of(fi):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name):
                    reason = self._taint_reason(n.value, tainted, amap)
                    if reason:
                        tainted.setdefault(n.targets[0].id, reason)
        return tainted

    def _taint_reason(self, expr, tainted_locals, amap):
        """Why this expression's value can differ across processes (a
        human-readable source description), or None."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr in _TAINT_NAMES \
                    and isinstance(n.ctx, ast.Load):
                return "the process rank ('%s')" % expr_text(n)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                if n.id in tainted_locals:
                    return tainted_locals[n.id]
                if n.id in _TAINT_NAMES:
                    return "the process rank ('%s')" % n.id
            if isinstance(n, ast.Call):
                f = n.func
                origin = resolve_origin(f, amap) \
                    if isinstance(f, (ast.Name, ast.Attribute)) else None
                base = origin.rsplit(".", 1)[-1] if origin else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                if origin in _CLOCK_ORIGINS:
                    return "the wall clock (%s())" % origin
                if origin and _is_rng_origin(origin):
                    return "the global RNG (%s())" % origin
                if base in _TAINT_CALL_BASENAMES:
                    return "the process identity (%s())" % (origin or base)
                if base == "fire" and origin \
                        and origin.endswith("faults.fire"):
                    return "fault injection (%s())" % origin
        return None

    def _arm_labels(self, fi, stmts):
        """Collective labels reachable from a statement list (direct
        events + call-edge closures; nested defs excluded)."""
        labels = set()
        direct = self._labels.get(fi, {})
        edges = self._edges.get(fi, {})
        alias = self._alias_edges.get(fi, {})
        stack = list(stmts)
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
                continue
            if isinstance(n, ast.Call):
                key = (n.lineno, n.col_offset)
                labels |= direct.get(key, set())
                callee = edges.get(key) or alias.get(key)
                if callee is not None:
                    labels |= self._reach.get(callee, frozenset())
            stack.extend(ast.iter_child_nodes(n))
        return labels

    def divergences(self, fi):
        """[(If node, taint reason, arm-a labels, arm-b labels)] for
        every branch in ``fi`` whose condition derives from per-process
        state and whose arms reach DIFFERENT collective sequences. An
        arm that falls through (no return/raise/break/continue) also
        reaches the statements after the branch, so `if rank != 0:
        return` before a psum diverges too."""
        src = fi.src
        amap = self.graph.imports_of(src)
        tainted = self._taint_locals(fi, amap)
        out = []

        def walk_block(stmts):
            for i, st in enumerate(stmts):
                if isinstance(st, _FUNC_NODES + (ast.ClassDef,)):
                    continue
                if isinstance(st, ast.If):
                    reason = self._taint_reason(st.test, tainted, amap)
                    if reason:
                        suffix = self._arm_labels(fi, stmts[i + 1:])
                        a = self._arm_labels(fi, st.body)
                        b = self._arm_labels(fi, st.orelse)
                        if not _terminates(st.body):
                            a = a | suffix
                        if not _terminates(st.orelse):
                            b = b | suffix
                        if a != b:
                            out.append((st, reason, a, b))
                for field, value in ast.iter_fields(st):
                    if isinstance(value, list) and value \
                            and isinstance(value[0], ast.stmt):
                        walk_block(value)
                    elif isinstance(value, list):
                        for v in value:
                            if isinstance(v, ast.excepthandler):
                                walk_block(v.body)
        walk_block(fi.node.body)
        return out

    def stats(self):
        n_sites = sum(len(s) for _c, s in self._fn.values())
        n_host = sum(1 for _c, ss in self._fn.values()
                     for s in ss if s.host)
        n_cross = sum(len(c) for c, _s in self._fn.values())
        return {
            "collective_sites": n_sites,
            "collective_host_sites": n_host,
            "gate_crossings": n_cross,
        }
