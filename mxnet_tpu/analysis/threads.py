"""mxsync's thread model: static thread roots + runs-on-roots sets.

The runtime is quietly very threaded — the serving coalescer and its
resolver pool, the flight sampler and metrics HTTP server, the
heartbeat beat loop, io/dataloader prefetch workers, plus the
asynchronous entry points Python itself provides (``atexit``/signal
handlers, ``sys.excepthook``/``threading.excepthook``,
``weakref.finalize`` callbacks, which cyclic GC may run on any
thread). Every one of those is a THREAD ROOT: a function whose body
executes concurrently with (or asynchronously to) the main control
flow. This module enumerates them statically:

* ``threading.Thread(target=f)`` / ``threading.Timer(t, f)``;
* ``pool.submit(f, ...)`` where the receiver is a
  ``concurrent.futures.ThreadPoolExecutor`` (local construction or a
  ``self.<attr>`` constructed anywhere in the class);
* ``ThreadingHTTPServer((host, port), Handler)`` — every method of the
  handler class runs on a server thread;
* ``atexit.register(f)``, ``signal.signal(sig, f)``,
  ``weakref.finalize(obj, f)``;
* ``sys.excepthook = f`` / ``threading.excepthook = f`` assignments.

From each root's target the *runs-on-roots* relation propagates over
``call`` AND ``ref`` edges of the mxflow call graph (a function a
thread-rooted function passes somewhere as a value runs under that
root too). Functions reachable from no root run under the implicit
``<main>`` root; a function reachable both ways carries both. The
``thread-race`` rule then reports a ``self.<attr>``/module-global
written under one root and touched under a different root with an
empty lockset intersection — with BOTH witness chains (root
registration site -> ... -> access) in the finding.

Also here (shared with the ``lockset`` rule): the RacerD-style
ENTRY-lockset fixpoint — the meet, over every resolved call site, of
the locks a function's callers hold at the call.
"""
from __future__ import annotations

import ast

from . import callgraph as cg
from .core import resolve_origin

MAIN_ROOT = "<main>"

# constructors whose instances fan work out to worker threads via
# ``.submit(fn, ...)``
_POOL_FACTORIES = {"concurrent.futures.ThreadPoolExecutor",
                   "concurrent.futures.thread.ThreadPoolExecutor"}

_SERVER_FACTORIES = {"http.server.ThreadingHTTPServer",
                     "http.server.HTTPServer",
                     "socketserver.ThreadingTCPServer"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class ThreadRoot:
    """One statically-discovered thread entry point."""

    __slots__ = ("kind", "target", "src", "line", "index")

    def __init__(self, kind, target, src, line, index):
        self.kind = kind                # "thread"/"timer"/"pool"/...
        self.target = target            # FuncInfo whose body runs there
        self.src = src                  # registration file
        self.line = line                # registration line
        self.index = index

    def label(self):
        return "%s '%s' (registered at %s:%d)" % (
            self.kind, self.target.qualname, self.src.display, self.line)

    def __repr__(self):
        return "ThreadRoot(%s)" % self.label()


def entry_locksets(graph, summ, members, self_locks, member_set=None,
                   require_private=True):
    """Locks guaranteed held on ENTRY to each of ``members``, via the
    meet over resolved call sites (RacerD's treatment): a helper called
    only from inside ``with lock:`` blocks counts as locked with no
    annotation; ONE bare call site (or an escape as a value — a ref
    edge means anyone may invoke it later, lock-free) drops it to the
    empty meet. ``member_set`` bounds the trusted caller universe (the
    class for attr locksets, the file for module-global locksets) —
    a caller outside it contributes the empty set. ONE implementation
    shared by the ``lockset`` inference rule and the ``thread-race``
    reports so their notions of "locked" can never drift — and ONE
    memoized result per (members, locks) on the Summaries object, so
    the two rules computing the same class's meet in one run pay for
    it once."""
    members = list(members)
    trusted = set(member_set if member_set is not None else members)
    cache = getattr(summ, "_entry_cache", None)
    cache_key = None
    if cache is not None:
        # fi.line disambiguates branch-defined same-named defs that
        # share a (display, qualname) key
        cache_key = (tuple(sorted((f.key, f.line) for f in members)),
                     tuple(sorted(self_locks)),
                     tuple(sorted((f.key, f.line) for f in trusted)),
                     require_private)
        got = cache.get(cache_key)
        if got is not None:
            return got

    def eligible(fi):
        if require_private and (not fi.name.startswith("_")
                                or fi.name.startswith("__")):
            return False
        return bool(graph.callers(fi)) \
            and not graph.callers(fi, kinds=(cg.REF,))

    entry = {fi: (self_locks if eligible(fi) else frozenset())
             for fi in members}
    for _round in range(len(members) + 2):
        changed = False
        for fi in members:
            if not eligible(fi):
                continue
            new = None
            for caller, line, col in graph.callers(fi):
                if caller not in trusted:
                    new = frozenset()       # callable from outside
                    break
                held = summ.facts_of(caller).calls_held.get(
                    (line, col), frozenset()) & self_locks
                eff = held | entry.get(caller, frozenset())
                new = eff if new is None else (new & eff)
            if new is None:
                new = frozenset()
            if new != entry[fi]:
                entry[fi] = new
                changed = True
        if not changed:
            break
    if cache is not None:
        cache[cache_key] = entry
    return entry


def _module_scope_nodes(tree, types):
    """Nodes of the given types executing at import time (class bodies
    included, function bodies NOT — those belong to their FuncInfo's
    own scan, with the right scope for registration-edge exclusion)."""
    stack = [tree]
    while stack:
        n = stack.pop()
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _FUNC_NODES):
                continue
            if isinstance(child, types):
                yield child
            stack.append(child)


class ThreadModel:
    """Thread roots + runs-on-roots over one Project's call graph."""

    def __init__(self, project, graph):
        self.project = project
        self.graph = graph
        self.roots = []                  # [ThreadRoot]
        self._roots_of = {}              # FuncInfo -> set of root indices
        self._pred = {}                  # root idx -> {fi: (parent, line)}
        self._reg_edges = set()          # (caller, callee, line, col)
        self._main = set()               # FuncInfo on the main root
        self._targets = set()
        self._collect_roots()
        self._propagate()

    # -- root discovery -----------------------------------------------------
    def _resolve_callback(self, src, scope, arg):
        """FuncInfo a callback expression lands on, or None. Mirrors
        the ref-edge resolution in the call graph (Name, self/cls
        attribute) so a root's target is exactly the node the ref edge
        points at."""
        graph = self.graph
        if isinstance(arg, ast.Name):
            got = graph.resolve_name(src, scope, arg.id)
            if got is not None and got[0] == "func":
                return got[1]
        elif isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in ("self", "cls") \
                and scope is not None and scope.self_class is not None:
            return graph._lookup_method(scope.self_class, arg.attr)
        return None

    def _handler_class_methods(self, src, arg):
        """Every method of a server handler CLASS passed by name —
        matched within the same file (handler classes are typically
        nested inside the function starting the server, so scoped
        resolution cannot see them)."""
        if not isinstance(arg, ast.Name):
            return []
        out = []
        for ci in self.graph.classes:
            if ci.src is src and ci.node.name == arg.id:
                out.extend(ci.methods.values())
        return out

    def _pool_attrs(self):
        """{(ClassInfo, attr name)} of self-attributes constructed as
        thread pools anywhere in their class."""
        out = set()
        for fi in self.graph.functions:
            if fi.self_class is None:
                continue
            amap = self.graph.imports_of(fi.src)
            for n in self.graph.nodes_of(fi):
                if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.value, ast.Call)):
                    continue
                t = n.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and resolve_origin(n.value.func, amap) \
                        in _POOL_FACTORIES:
                    out.add((fi.self_class, t.attr))
        return out

    def _add_root(self, kind, target, src, call_or_line, scope):
        if target is None:
            return
        line = getattr(call_or_line, "lineno", call_or_line)
        col = getattr(call_or_line, "col_offset", 0)
        root = ThreadRoot(kind, target, src, line, len(self.roots))
        self.roots.append(root)
        self._targets.add(target)
        if scope is not None:
            # the ref edge the call graph drew for this registration
            # must not carry the MAIN root into the target's body: the
            # registration runs on the registering thread, the TARGET
            # runs on the new root
            self._reg_edges.add((scope, target, line, col))

    def _scan_calls(self, src, scope, calls, pool_attrs, local_pools):
        amap = self.graph.imports_of(src)
        for call in calls:
            f = call.func
            kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
            origin = resolve_origin(f, amap) \
                if isinstance(f, (ast.Name, ast.Attribute)) else None
            if origin == "threading.Thread":
                cb = kwargs.get("target")
                self._add_root("thread",
                               self._resolve_callback(src, scope, cb),
                               src, call, scope)
            elif origin == "threading.Timer":
                cb = kwargs.get("function") or (
                    call.args[1] if len(call.args) > 1 else None)
                self._add_root("timer",
                               self._resolve_callback(src, scope, cb),
                               src, call, scope)
            elif origin == "weakref.finalize" and len(call.args) >= 2:
                self._add_root("finalizer",
                               self._resolve_callback(src, scope,
                                                      call.args[1]),
                               src, call, scope)
            elif origin == "atexit.register" and call.args:
                self._add_root("atexit",
                               self._resolve_callback(src, scope,
                                                      call.args[0]),
                               src, call, scope)
            elif origin == "signal.signal" and len(call.args) >= 2:
                self._add_root("signal-handler",
                               self._resolve_callback(src, scope,
                                                      call.args[1]),
                               src, call, scope)
            elif origin in _SERVER_FACTORIES and len(call.args) >= 2:
                for m in self._handler_class_methods(src, call.args[1]):
                    self._add_root("http-handler", m, src, call, scope)
            elif isinstance(f, ast.Attribute) and f.attr == "submit" \
                    and call.args:
                recv = f.value
                is_pool = False
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self" \
                        and scope is not None \
                        and (scope.self_class, recv.attr) in pool_attrs:
                    is_pool = True
                elif isinstance(recv, ast.Name) \
                        and recv.id in local_pools:
                    is_pool = True
                if is_pool:
                    self._add_root(
                        "pool-worker",
                        self._resolve_callback(src, scope, call.args[0]),
                        src, call, scope)

    def _scan_hook_assigns(self, src, scope, nodes):
        """``sys.excepthook = f`` / ``threading.excepthook = f``."""
        amap = self.graph.imports_of(src)
        for n in nodes:
            if not isinstance(n, ast.Assign):
                continue
            for t in n.targets:
                if isinstance(t, ast.Attribute) and resolve_origin(
                        t, amap) in ("sys.excepthook",
                                     "threading.excepthook"):
                    self._add_root(
                        "excepthook",
                        self._resolve_callback(src, scope, n.value),
                        src, n, scope)

    def _local_pools(self, src, nodes):
        amap = self.graph.imports_of(src)
        out = set()
        for n in nodes:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, ast.Call) \
                    and resolve_origin(n.value.func, amap) \
                    in _POOL_FACTORIES:
                out.add(n.targets[0].id)
        return out

    def _collect_roots(self):
        pool_attrs = self._pool_attrs()
        for src in self.project.sources:
            self._scan_calls(src, None,
                             _module_scope_nodes(src.tree, ast.Call),
                             pool_attrs, set())
            # MODULE-scope assigns only: a hook assignment inside a
            # function body is that function's registration (scanned
            # below with its scope, so the reg edge is excluded from
            # main propagation) — walking the whole tree here would
            # register the same root twice and fabricate cross-root
            # races between the two clones
            self._scan_hook_assigns(
                src, None, _module_scope_nodes(src.tree, ast.Assign))
        for fi in self.graph.functions:
            src = fi.src
            nodes = self.graph.nodes_of(fi)
            self._scan_calls(src, fi,
                             (n for n in nodes
                              if isinstance(n, ast.Call)),
                             pool_attrs, self._local_pools(src, nodes))
            self._scan_hook_assigns(
                src, fi, (n for n in nodes if isinstance(n, ast.Assign)))

    # -- propagation ---------------------------------------------------------
    def _propagate(self):
        graph = self.graph
        # thread roots flow target -> callees over call AND ref edges —
        # except REGISTRATION edges: thread-rooted code spawning a NEW
        # thread (Thread(target=self._inner) inside a Thread target)
        # hands _inner to the new thread, not to its own; following
        # that edge would fabricate a cross-root race between two
        # points of one sequential spawn chain (_inner gets its own
        # root from its own registration)
        for root in self.roots:
            pred = {root.target: None}
            queue = [root.target]
            while queue:
                f = queue.pop()
                self._roots_of.setdefault(f, set()).add(root.index)
                for callee, line, col in graph.callees(
                        f, kinds=(cg.CALL, cg.REF)):
                    if callee in pred:
                        continue
                    if (f, callee, line, col) in self._reg_edges:
                        continue
                    pred[callee] = (f, line)
                    queue.append(callee)
            self._pred[root.index] = pred
        # the implicit main root: seeded at functions nobody in-graph
        # calls that are not thread targets themselves (public API,
        # module-level-invoked helpers), flowing over call edges and
        # over ref edges that are NOT thread registrations
        seeds = [fi for fi in graph.functions
                 if fi not in self._targets
                 and not graph.callers(fi, kinds=(cg.CALL,))]
        queue = list(seeds)
        self._main.update(seeds)
        while queue:
            f = queue.pop()
            for callee, line, col in graph.callees(
                    f, kinds=(cg.CALL, cg.REF)):
                if callee in self._main:
                    continue
                if (f, callee, line, col) in self._reg_edges:
                    continue
                self._main.add(callee)
                queue.append(callee)

    # -- queries -------------------------------------------------------------
    def effective_roots(self, fi):
        """Root indices ``fi`` may run under; ``MAIN_ROOT`` stands in
        for the main thread. Never empty: a function the model cannot
        place defaults to main (conservative-quiet)."""
        out = set(self._roots_of.get(fi, ()))
        if fi in self._main or not out:
            out.add(MAIN_ROOT)
        return frozenset(out)

    def chain(self, root_index, fi):
        """Witness hops from the root's target down to ``fi``:
        ``[(FuncInfo, call line in the parent's file), ...]`` —
        empty when ``fi`` IS the target."""
        if root_index == MAIN_ROOT:
            return []
        pred = self._pred.get(root_index, {})
        hops = []
        cur = fi
        while pred.get(cur) is not None:
            parent, line = pred[cur]
            hops.append((cur, line))
            cur = parent
        hops.reverse()
        return hops

    def describe(self, root_index, fi):
        """Human chain text 'root ... -> fn' plus the display files the
        chain crosses (for Finding.via)."""
        if root_index == MAIN_ROOT:
            return ("the main thread", {fi.src.display})
        root = self.roots[root_index]
        via = {root.src.display, root.target.src.display, fi.src.display}
        text = root.label()
        prev = root.target
        for hop, line in self.chain(root_index, fi):
            text += " -> %s (called at %s:%d)" % (
                hop.name, prev.src.display, line)
            via.add(hop.src.display)
            prev = hop
        return (text, via)

    def stats(self):
        return {
            "thread_roots": len(self.roots),
            "thread_rooted_functions": len(self._roots_of),
        }
