"""The mxlint framework: sources, suppressions, baseline, rule driver.

Everything here is stdlib-only (``ast`` + ``json``) and import-light so
the lint stage of ``tools/run_checks.sh`` runs without the native build
or a jax import. The comment grammar this module parses out of raw
source lines (the :mod:`ast` tree drops comments):

* ``# mxlint: disable=<rule>[,<rule>...] -- <justification>`` —
  suppress those rules' findings on this line (trailing form) or on the
  next line (standalone-comment form). The justification text after
  ``--`` is REQUIRED: a suppression that doesn't say why is reported as
  an ``mxlint-suppression`` finding instead of honoured.
* ``# guarded by: <lock expr>`` — trailing on an assignment: the
  assigned attribute/global is only touched under ``with <lock expr>:``
  (the lock-discipline rule's annotation).
* ``# mxlint: hot`` — trailing on a ``def`` line (or standalone on the
  line above it): the function is a hot path the host-sync rule polices.
* ``# mxlint: donates <indices>`` — trailing on a call line: the call
  donates the buffers at these 0-based positional indices (``0,1`` or
  ``0-3``), for callees whose ``donate_argnums`` the analyzer cannot see
  locally.
* ``the ONE instrumented jit site`` — the executor's marker comment;
  the jit-site rule allows exactly this site.
"""
from __future__ import annotations

import ast
import json
import os
import re

# rule ids, in report order. The list lives here (not in the rules
# package) so ``--list-rules``, suppression validation and the tests
# share one source of truth.
ALL_RULE_IDS = ("jit-site", "dispatch-hook", "lock-discipline",
                "host-sync", "donation-safety", "registry-consistency")

# the rule id bad suppression comments are reported under (not
# suppressible itself — a broken suppression must not hide)
SUPPRESSION_RULE = "mxlint-suppression"

# rules the baseline may never cover either: a broken suppression or an
# unparseable file means the gate itself is compromised, so neither
# --update-baseline nor a hand-edited entry can grandfather them
NEVER_BASELINED = frozenset((SUPPRESSION_RULE, "parse-error"))

_DISABLE_RE = re.compile(
    r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*))?$")
_GUARD_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z0-9_.\[\]'\"]+)\s*$")
_HOT_RE = re.compile(r"#\s*mxlint:\s*hot\s*$")
_DONATES_RE = re.compile(r"#\s*mxlint:\s*donates\s+([0-9,\- ]+)\s*$")
JIT_SITE_MARKER = "the ONE instrumented jit site"


class Finding:
    """One rule violation at a source location. ``anchor`` (the stripped
    text of the finding's line) is the line-drift-tolerant half of the
    baseline identity ``(rule, path, anchor)`` — a finding keeps its
    baseline entry when unrelated edits move it, and loses it when the
    offending line itself changes (which is exactly when a human should
    look again)."""

    __slots__ = ("rule", "path", "line", "col", "message", "anchor")

    def __init__(self, rule, path, line, col, message, anchor=""):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.anchor = anchor

    def key(self):
        return (self.rule, self.path, self.anchor)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "anchor": self.anchor}

    def render(self):
        return "%s:%d:%d: %s: %s" % (self.path, self.line, self.col,
                                     self.rule, self.message)

    def __repr__(self):
        return "Finding(%s)" % self.render()


def _parse_donate_indices(spec):
    """``"0,1"`` / ``"0-3"`` -> tuple of ints, or None on a bad spec."""
    out = []
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        if "-" in term:
            lo, _, hi = term.partition("-")
            try:
                lo, hi = int(lo), int(hi)
            except ValueError:
                return None
            if hi < lo:
                return None
            out.extend(range(lo, hi + 1))
        else:
            try:
                out.append(int(term))
            except ValueError:
                return None
    return tuple(sorted(set(out))) or None


class Source:
    """One parsed file: the AST plus everything the comment grammar
    declares (suppressions, guard annotations, hot markers, donation
    markers, the instrumented-jit-site marker)."""

    def __init__(self, path, text, display_path=None):
        self.path = path
        self.display = display_path or path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)     # caller handles SyntaxError
        # line -> (frozenset of rule ids, justification)
        self.suppressions = {}
        # findings produced by the comment grammar itself
        self.grammar_findings = []
        self.guards = {}                # line -> lock expr string
        self.hot_lines = set()
        self.donates = {}               # line -> tuple of donated indices
        self.jit_marker_lines = set()
        self._scan_comments()
        self._parents = None
        self._aliases = None

    # -- comment grammar ----------------------------------------------------
    def _scan_comments(self):
        for i, raw in enumerate(self.lines, 1):
            if "#" not in raw:
                continue
            # the marker only counts as a COMMENT (text after '#') — a
            # string literal or docstring mentioning it is not a site
            if JIT_SITE_MARKER in raw.split("#", 1)[1]:
                self.jit_marker_lines.add(i)
            stripped = raw.strip()
            standalone = stripped.startswith("#")
            m = _DISABLE_RE.search(raw)
            if m:
                rules = frozenset(r.strip() for r in m.group(1).split(",")
                                  if r.strip())
                just = (m.group(2) or "").strip()
                bad = None
                if not rules:
                    bad = "no rule ids"
                elif not all(r in ALL_RULE_IDS for r in rules):
                    bad = "unknown rule id(s): %s" % ", ".join(
                        sorted(r for r in rules if r not in ALL_RULE_IDS))
                elif not just:
                    bad = ("missing justification — write "
                           "'# mxlint: disable=%s -- <why this is safe>'"
                           % ",".join(sorted(rules)))
                if bad:
                    self.grammar_findings.append(Finding(
                        SUPPRESSION_RULE, self.display, i, 0,
                        "unusable suppression (%s); the finding it "
                        "meant to silence will still report" % bad,
                        anchor=stripped))
                else:
                    target = i + 1 if standalone else i
                    self.suppressions.setdefault(target, []).append(
                        (rules, just))
            m = _GUARD_RE.search(raw)
            if m:
                self.guards[i] = m.group(1)
            if _HOT_RE.search(raw):
                # standalone marker arms the NEXT line's def; trailing
                # marker arms its own line
                self.hot_lines.add(i + 1 if standalone else i)
            m = _DONATES_RE.search(raw)
            if m:
                idx = _parse_donate_indices(m.group(1))
                if idx is None:
                    self.grammar_findings.append(Finding(
                        SUPPRESSION_RULE, self.display, i, 0,
                        "unparseable '# mxlint: donates %s' marker"
                        % m.group(1), anchor=stripped))
                else:
                    self.donates[i] = idx

    def suppressed(self, rule, line):
        """The justification string when ``rule`` is suppressed at
        ``line``, else None."""
        for rules, just in self.suppressions.get(line, ()):
            if rule in rules:
                return just
        return None

    def anchor_for(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule, node_or_line, message):
        line = getattr(node_or_line, "lineno", node_or_line)
        col = getattr(node_or_line, "col_offset", 0)
        return Finding(rule, self.display, line, col, message,
                       anchor=self.anchor_for(line))

    # -- shared AST helpers --------------------------------------------------
    def parents(self):
        """{child node: parent node} over the whole tree (built once)."""
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def import_aliases(self):
        """{local name: dotted origin} for every import in the file —
        ``import jax.experimental.pjit as P`` maps ``P`` to
        ``jax.experimental.pjit``; ``from jax import jit as J`` maps
        ``J`` to ``jax.jit``. Resolution is textual (no module is ever
        imported). Built once — four of the six rules ask for it."""
        if self._aliases is not None:
            return self._aliases
        aliases = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = \
                        "%s.%s" % (node.module, a.name)
        self._aliases = aliases
        return aliases

    def resolve(self, node, aliases):
        """Dotted origin of a Name/Attribute expression under the
        file's import aliases, or None (not import-rooted)."""
        if isinstance(node, ast.Name):
            return aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value, aliases)
            if base is None:
                return None
            return "%s.%s" % (base, node.attr)
        return None


def expr_text(node):
    """Canonical text of a small expression (lock names, with-items)."""
    try:
        return ast.unparse(node)
    except Exception:       # pragma: no cover - malformed synthetic nodes
        return ""


def is_self_attr(node, name=None):
    """True when ``node`` is ``self.<attr>`` (optionally a specific
    attr) — shared by the lock-discipline and donation rules."""
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (name is None or node.attr == name))


def iter_python_files(paths):
    """Expand files/directories into sorted .py file paths (dirs walk
    recursively, ``__pycache__`` skipped). Nonexistent inputs raise
    ``FileNotFoundError`` — a typo'd CLI path must not read as clean."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


class Project:
    """Every parsed Source of one run — what cross-file registry passes
    see. Files that fail to parse land in ``parse_errors`` as findings
    (a syntax error in a linted file is a finding, not a crash)."""

    def __init__(self, root=None):
        self.root = root
        self.sources = []
        self.parse_errors = []

    def add_file(self, path):
        display = os.path.relpath(path, self.root) if self.root else path
        display = display.replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            self.parse_errors.append(Finding(
                "parse-error", display, 0, 0, "unreadable: %s" % e))
            return None
        try:
            src = Source(path, text, display_path=display)
        except SyntaxError as e:
            self.parse_errors.append(Finding(
                "parse-error", display, e.lineno or 0, e.offset or 0,
                "syntax error: %s" % e.msg))
            return None
        self.sources.append(src)
        return src


class Baseline:
    """The committed grandfather file: findings listed here report as
    ``baselined`` (exit 0) instead of failing the run.

    Entries are ``{rule, path, anchor, count}``; identity is
    :meth:`Finding.key`. The loader TOLERATES entries that no longer
    match any current finding — they surface as ``stale`` warnings and
    are pruned by ``--update-baseline``, never an error (deleting the
    offending code must not break the lint that flagged it)."""

    def __init__(self, path=None):
        self.path = path
        self.entries = {}        # key -> allowed count
        self.load_warnings = []

    @classmethod
    def load(cls, path):
        bl = cls(path)
        if path is None or not os.path.exists(path):
            return bl
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            bl.load_warnings.append("baseline %s unreadable (%s) — "
                                    "running without it" % (path, e))
            return bl
        items = data.get("findings", []) if isinstance(data, dict) else []
        for ent in items:
            if not isinstance(ent, dict):
                bl.load_warnings.append(
                    "baseline entry %r is not an object — skipped" % (ent,))
                continue
            try:
                key = (str(ent["rule"]), str(ent["path"]),
                       str(ent["anchor"]))
                count = max(1, int(ent.get("count", 1)))
            except KeyError as e:
                bl.load_warnings.append(
                    "baseline entry missing field %s — skipped" % e)
                continue
            except (TypeError, ValueError):
                bl.load_warnings.append(
                    "baseline entry %r has a non-integer count — "
                    "counted as 1" % (ent.get("anchor"),))
                count = 1
            bl.entries[key] = bl.entries.get(key, 0) + count
        return bl

    def partition(self, findings):
        """(kept, baselined, stale) — ``kept`` are findings the baseline
        does not cover; ``stale`` are baseline entries with no matching
        current finding (candidates for pruning)."""
        remaining = dict(self.entries)
        kept, baselined = [], []
        for f in findings:
            k = f.key()
            if f.rule in NEVER_BASELINED:
                kept.append(f)
            elif remaining.get(k, 0) > 0:
                remaining[k] -= 1
                baselined.append(f)
            else:
                kept.append(f)
        stale = [{"rule": r, "path": p, "anchor": a, "count": n}
                 for (r, p, a), n in sorted(remaining.items()) if n > 0]
        return kept, baselined, stale

    @staticmethod
    def render(findings):
        """The JSON document ``--update-baseline`` writes: every CURRENT
        unsuppressed finding, stale entries implicitly pruned.
        :data:`NEVER_BASELINED` rules are excluded — they must keep
        failing the gate until the code is fixed."""
        counts = {}
        for f in findings:
            if f.rule in NEVER_BASELINED:
                continue
            counts[f.key()] = counts.get(f.key(), 0) + 1
        return {
            "version": 1,
            "comment": "grandfathered mxlint findings; regenerate with "
                       "tools/mxlint.py --update-baseline <paths>",
            "findings": [
                {"rule": r, "path": p, "anchor": a, "count": n}
                for (r, p, a), n in sorted(counts.items())],
        }


class Report:
    """One run's outcome: what fails the gate (``findings``), what was
    silenced and why (``suppressed``/``baselined``), and the baseline
    hygiene warnings (``stale_baseline``)."""

    def __init__(self, findings, suppressed, baselined, stale_baseline,
                 warnings, paths, rules):
        self.findings = findings
        self.suppressed = suppressed      # [(finding, justification)]
        self.baselined = baselined
        self.stale_baseline = stale_baseline
        self.warnings = warnings
        self.paths = paths
        self.rules = rules

    @property
    def clean(self):
        return not self.findings

    def counts(self):
        out = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self):
        return {
            "version": 1,
            "paths": list(self.paths),
            "rules": list(self.rules),
            "clean": self.clean,
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [dict(f.to_dict(), justification=j)
                           for f, j in self.suppressed],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "warnings": list(self.warnings),
        }

    def render_text(self):
        lines = []
        for w in self.warnings:
            lines.append("warning: %s" % w)
        for ent in self.stale_baseline:
            lines.append(
                "warning: stale baseline entry (no longer found): "
                "%(rule)s %(path)s %(anchor)r — prune with "
                "--update-baseline" % ent)
        for f in self.findings:
            lines.append(f.render())
        lines.append(
            "mxlint: %d finding(s), %d suppressed, %d baselined, "
            "%d stale baseline entr%s"
            % (len(self.findings), len(self.suppressed),
               len(self.baselined), len(self.stale_baseline),
               "y" if len(self.stale_baseline) == 1 else "ies"))
        return "\n".join(lines)


def _load_rules(rule_ids=None):
    from . import rules as _rules
    table = _rules.rule_table()
    ids = list(rule_ids) if rule_ids else list(ALL_RULE_IDS)
    unknown = [r for r in ids if r not in table]
    if unknown:
        raise ValueError("unknown rule id(s): %s (known: %s)"
                         % (", ".join(unknown), ", ".join(table)))
    return [(rid, table[rid]) for rid in ids]


def run(paths, rules=None, baseline=None, root=None):
    """Analyze ``paths`` (files/dirs) with the given rule ids (default:
    all) against ``baseline`` (a path, a :class:`Baseline`, or None).
    Returns a :class:`Report`. ``root`` rebases display paths (the CLI
    passes the repo root so baseline entries stay machine-independent).
    """
    files = iter_python_files(paths)
    project = Project(root=root)
    for path in files:
        project.add_file(path)

    selected = _load_rules(rules)
    raw = list(project.parse_errors)
    for src in project.sources:
        raw.extend(src.grammar_findings)
        for _rid, rule in selected:
            check = getattr(rule, "check_source", None)
            if check is not None:
                raw.extend(check(src, project))
    for _rid, rule in selected:
        check = getattr(rule, "check_project", None)
        if check is not None:
            raw.extend(check(project))

    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    by_display = {s.display: s for s in project.sources}
    unsuppressed, suppressed = [], []
    for f in raw:
        src = by_display.get(f.path)
        just = None
        if src is not None and f.rule != SUPPRESSION_RULE:
            just = src.suppressed(f.rule, f.line)
        if just is not None:
            suppressed.append((f, just))
        else:
            unsuppressed.append(f)

    if baseline is None or isinstance(baseline, Baseline):
        bl = baseline or Baseline()
    else:
        bl = Baseline.load(baseline)
    kept, baselined, stale = bl.partition(unsuppressed)
    return Report(kept, suppressed, baselined, stale,
                  list(bl.load_warnings),
                  [p.replace(os.sep, "/") for p in paths],
                  [rid for rid, _ in selected])
