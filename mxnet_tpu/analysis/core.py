"""The mxlint framework: sources, suppressions, baseline, rule driver.

Everything here is stdlib-only (``ast`` + ``json``) and import-light so
the lint stage of ``tools/run_checks.sh`` runs without the native build
or a jax import. The comment grammar this module parses out of raw
source lines (the :mod:`ast` tree drops comments):

* ``# mxlint: disable=<rule>[,<rule>...] -- <justification>`` —
  suppress those rules' findings on this line (trailing form) or on the
  next line (standalone-comment form). The justification text after
  ``--`` is REQUIRED: a suppression that doesn't say why is reported as
  an ``mxlint-suppression`` finding instead of honoured.
* ``# guarded by: <lock expr>`` — trailing on an assignment: the
  assigned attribute/global is only touched under ``with <lock expr>:``
  (the lock-discipline rule's annotation).
* ``# mxlint: hot`` — trailing on a ``def`` line (or standalone on the
  line above it): the function is a hot path the host-sync rule polices.
* ``# mxlint: donates <indices>`` — trailing on a call line: the call
  donates the buffers at these 0-based positional indices (``0,1`` or
  ``0-3``), for callees whose ``donate_argnums`` the analyzer cannot see
  locally.
* ``the ONE instrumented jit site`` — the executor's marker comment;
  the jit-site rule allows exactly this site.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import time

# rule ids, in report order. The list lives here (not in the rules
# package) so ``--list-rules``, suppression validation and the tests
# share one source of truth. ``lockset`` and ``trace-purity`` are the
# mxflow interprocedural additions (ISSUE 9); ``host-sync`` and
# ``donation-safety`` gained interprocedural layers under their
# existing ids; ``thread-race`` and ``collective-discipline`` are the
# mxsync concurrency families (ISSUE 13); ``future-lifecycle``,
# ``resource-release`` and ``torn-state-on-raise`` are the mxlife
# typestate/exception-path family (ISSUE 14).
ALL_RULE_IDS = ("jit-site", "dispatch-hook", "lock-discipline",
                "lockset", "thread-race", "host-sync", "trace-purity",
                "donation-safety", "collective-discipline",
                "future-lifecycle", "resource-release",
                "torn-state-on-raise", "registry-consistency")

# the rule id bad suppression comments are reported under (not
# suppressible itself — a broken suppression must not hide)
SUPPRESSION_RULE = "mxlint-suppression"

# rules the baseline may never cover either: a broken suppression or an
# unparseable file means the gate itself is compromised, so neither
# --update-baseline nor a hand-edited entry can grandfather them
NEVER_BASELINED = frozenset((SUPPRESSION_RULE, "parse-error"))

_DISABLE_RE = re.compile(
    r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*))?$")
_GUARD_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z0-9_.\[\]'\"]+)\s*$")
_HOT_RE = re.compile(r"#\s*mxlint:\s*hot\s*$")
_DONATES_RE = re.compile(r"#\s*mxlint:\s*donates\s+([0-9,\- ]+)\s*$")
# mxsync's collective-channel marker: trailing on a ``def`` line it
# declares the function a cross-process collective primitive (every
# call to it is a collective site on that channel); trailing on a CALL
# line it marks/overrides that one site's channel. Standalone-comment
# form arms the next line, like ``# mxlint: hot``.
_COLLECTIVE_RE = re.compile(
    r"#\s*mxsync:\s*collective(?:\s+channel=([A-Za-z0-9_\-]+))?\s*$")
JIT_SITE_MARKER = "the ONE instrumented jit site"


class Finding:
    """One rule violation at a source location. ``anchor`` (the stripped
    text of the finding's line) is the line-drift-tolerant half of the
    baseline identity ``(rule, path, anchor)`` — a finding keeps its
    baseline entry when unrelated edits move it, and loses it when the
    offending line itself changes (which is exactly when a human should
    look again).

    ``via``: for chain-bearing findings, the display paths the witness
    chain passes through (root and intermediate hops). NOT part of the
    baseline identity — it exists so ``--changed`` subset mode can keep
    a sink-anchored finding whose chain crosses a touched file."""

    __slots__ = ("rule", "path", "line", "col", "message", "anchor",
                 "via")

    def __init__(self, rule, path, line, col, message, anchor="",
                 via=()):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.anchor = anchor
        self.via = tuple(via)

    def key(self):
        return (self.rule, self.path, self.anchor)

    def to_dict(self):
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message,
             "anchor": self.anchor}
        if self.via:
            d["via"] = list(self.via)
        return d

    def render(self):
        return "%s:%d:%d: %s: %s" % (self.path, self.line, self.col,
                                     self.rule, self.message)

    def __repr__(self):
        return "Finding(%s)" % self.render()


def _parse_donate_indices(spec):
    """``"0,1"`` / ``"0-3"`` -> tuple of ints, or None on a bad spec."""
    out = []
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        if "-" in term:
            lo, _, hi = term.partition("-")
            try:
                lo, hi = int(lo), int(hi)
            except ValueError:
                return None
            if hi < lo:
                return None
            out.extend(range(lo, hi + 1))
        else:
            try:
                out.append(int(term))
            except ValueError:
                return None
    return tuple(sorted(set(out))) or None


class Source:
    """One parsed file: the AST plus everything the comment grammar
    declares (suppressions, guard annotations, hot markers, donation
    markers, the instrumented-jit-site marker)."""

    def __init__(self, path, text, display_path=None):
        self.path = path
        self.display = display_path or path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)     # caller handles SyntaxError
        # line -> (frozenset of rule ids, justification)
        self.suppressions = {}
        # findings produced by the comment grammar itself
        self.grammar_findings = []
        self.guards = {}                # line -> lock expr string
        self.hot_lines = set()
        self.donates = {}               # line -> tuple of donated indices
        self.collective_marks = {}      # line -> channel string
        self.jit_marker_lines = set()
        self._scan_comments()
        self._parents = None
        self._aliases = None

    # -- comment grammar ----------------------------------------------------
    def _scan_comments(self):
        for i, raw in enumerate(self.lines, 1):
            if "#" not in raw:
                continue
            # the marker only counts as a COMMENT (text after '#') — a
            # string literal or docstring mentioning it is not a site
            if JIT_SITE_MARKER in raw.split("#", 1)[1]:
                self.jit_marker_lines.add(i)
            stripped = raw.strip()
            standalone = stripped.startswith("#")
            m = _DISABLE_RE.search(raw)
            if m:
                rules = frozenset(r.strip() for r in m.group(1).split(",")
                                  if r.strip())
                just = (m.group(2) or "").strip()
                bad = None
                if not rules:
                    bad = "no rule ids"
                elif not all(r in ALL_RULE_IDS for r in rules):
                    bad = "unknown rule id(s): %s" % ", ".join(
                        sorted(r for r in rules if r not in ALL_RULE_IDS))
                elif not just:
                    bad = ("missing justification — write "
                           "'# mxlint: disable=%s -- <why this is safe>'"
                           % ",".join(sorted(rules)))
                if bad:
                    self.grammar_findings.append(Finding(
                        SUPPRESSION_RULE, self.display, i, 0,
                        "unusable suppression (%s); the finding it "
                        "meant to silence will still report" % bad,
                        anchor=stripped))
                else:
                    target = i + 1 if standalone else i
                    self.suppressions.setdefault(target, []).append(
                        (rules, just))
            m = _GUARD_RE.search(raw)
            if m:
                self.guards[i] = m.group(1)
            if _HOT_RE.search(raw):
                # standalone marker arms the NEXT line's def; trailing
                # marker arms its own line
                self.hot_lines.add(i + 1 if standalone else i)
            m = _DONATES_RE.search(raw)
            if m:
                idx = _parse_donate_indices(m.group(1))
                if idx is None:
                    self.grammar_findings.append(Finding(
                        SUPPRESSION_RULE, self.display, i, 0,
                        "unparseable '# mxlint: donates %s' marker"
                        % m.group(1), anchor=stripped))
                else:
                    self.donates[i] = idx
            m = _COLLECTIVE_RE.search(raw)
            if m:
                # channel defaults to "step" (the fused-step channel,
                # matching CollectiveGate's own default)
                self.collective_marks[i + 1 if standalone else i] = \
                    m.group(1) or "step"

    def suppressed(self, rule, line):
        """The justification string when ``rule`` is suppressed at
        ``line``, else None."""
        for rules, just in self.suppressions.get(line, ()):
            if rule in rules:
                return just
        return None

    def anchor_for(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule, node_or_line, message, via=()):
        line = getattr(node_or_line, "lineno", node_or_line)
        col = getattr(node_or_line, "col_offset", 0)
        return Finding(rule, self.display, line, col, message,
                       anchor=self.anchor_for(line), via=via)

    # -- shared AST helpers --------------------------------------------------
    def parents(self):
        """{child node: parent node} over the whole tree (built once)."""
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def import_aliases(self):
        """{local name: dotted origin} for every import in the file —
        ``import jax.experimental.pjit as P`` maps ``P`` to
        ``jax.experimental.pjit``; ``from jax import jit as J`` maps
        ``J`` to ``jax.jit``. Resolution is textual (no module is ever
        imported). Built once — four of the six rules ask for it."""
        if self._aliases is not None:
            return self._aliases
        aliases = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = \
                        "%s.%s" % (node.module, a.name)
        self._aliases = aliases
        return aliases

    def resolve(self, node, aliases):
        """Dotted origin of a Name/Attribute expression under the
        file's import aliases, or None (not import-rooted)."""
        return resolve_origin(node, aliases)


def resolve_origin(node, aliases):
    """Dotted origin of a Name/Attribute expression under an alias
    map (falls back to the bare name chain), or None (not a
    name-rooted chain). THE resolver: core, callgraph and summaries
    all route through this one function so a fix here applies to the
    direct and interprocedural layers alike."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id, node.id)
    if isinstance(node, ast.Attribute):
        base = resolve_origin(node.value, aliases)
        if base is None:
            return None
        return "%s.%s" % (base, node.attr)
    return None


def expr_text(node):
    """Canonical text of a small expression (lock names, with-items)."""
    try:
        return ast.unparse(node)
    except Exception:       # pragma: no cover - malformed synthetic nodes
        return ""


def is_self_attr(node, name=None):
    """True when ``node`` is ``self.<attr>`` (optionally a specific
    attr) — shared by the lock-discipline and donation rules."""
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (name is None or node.attr == name))


def iter_python_files(paths):
    """Expand files/directories into sorted .py file paths (dirs walk
    recursively, ``__pycache__`` skipped). Nonexistent inputs raise
    ``FileNotFoundError`` — a typo'd CLI path must not read as clean."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


class Project:
    """Every parsed Source of one run — what cross-file registry passes
    see. Files that fail to parse land in ``parse_errors`` as findings
    (a syntax error in a linted file is a finding, not a crash)."""

    def __init__(self, root=None):
        self.root = root
        self.sources = []
        self.parse_errors = []
        self.timings = {}               # "callgraph"/"summaries" build s
        self.extra_stats = {}           # mxsync model stats for the report
        self._graph = None
        self._summaries = None
        self._threads = None
        self._collectives = None
        self._lifecycle = None

    def callgraph(self):
        """The mxflow call graph over every parsed source — built once
        per run, on first demand (rules that never go interprocedural
        never pay for it)."""
        if self._graph is None:
            from . import callgraph as _callgraph
            t0 = time.perf_counter()
            self._graph = _callgraph.build(self)
            self.timings["callgraph"] = time.perf_counter() - t0
        return self._graph

    def summaries(self):
        """Per-function effect summaries over :meth:`callgraph` —
        built once per run, on first demand."""
        if self._summaries is None:
            from . import summaries as _summaries
            graph = self.callgraph()
            t0 = time.perf_counter()
            self._summaries = _summaries.Summaries(self, graph)
            self.timings["summaries"] = time.perf_counter() - t0
        return self._summaries

    def threads(self):
        """The mxsync thread model (thread roots + runs-on-roots sets)
        over :meth:`callgraph` — built once per run, on first demand.
        Banks its stats (roots found, rooted functions) into
        ``extra_stats`` for the JSON report."""
        if self._threads is None:
            from . import threads as _threads
            graph = self.callgraph()
            t0 = time.perf_counter()
            self._threads = _threads.ThreadModel(self, graph)
            self.timings["threads"] = time.perf_counter() - t0
            self.extra_stats.update(self._threads.stats())
        return self._threads

    def collectives(self):
        """The mxsync collective model (site index, gate crossings,
        entry-gated channels) — built once per run, on first demand.
        Banks its stats (sites indexed, crossings) into
        ``extra_stats``."""
        if self._collectives is None:
            from . import collectives as _collectives
            graph = self.callgraph()
            t0 = time.perf_counter()
            self._collectives = _collectives.CollectiveModel(self, graph)
            self.timings["collectives"] = time.perf_counter() - t0
            self.extra_stats.update(self._collectives.stats())
        return self._collectives

    def lifecycle(self):
        """The mxlife lifecycle model (future classes, typestate sims,
        discharge fixpoint) over :meth:`callgraph` — built once per
        run, on first demand. Banks its stats (future classes,
        resolver functions, may-raise functions) into ``extra_stats``
        for the JSON report."""
        if self._lifecycle is None:
            from . import lifecycle as _lifecycle
            graph = self.callgraph()
            t0 = time.perf_counter()
            self._lifecycle = _lifecycle.LifecycleModel(self, graph)
            self.timings["lifecycle"] = time.perf_counter() - t0
            self.extra_stats.update(self._lifecycle.stats())
        return self._lifecycle

    def add_file(self, path):
        display = os.path.relpath(path, self.root) if self.root else path
        display = display.replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            self.parse_errors.append(Finding(
                "parse-error", display, 0, 0, "unreadable: %s" % e))
            return None
        try:
            src = Source(path, text, display_path=display)
        except SyntaxError as e:
            self.parse_errors.append(Finding(
                "parse-error", display, e.lineno or 0, e.offset or 0,
                "syntax error: %s" % e.msg))
            return None
        self.sources.append(src)
        return src


# ---------------------------------------------------------------------------
# dependency cache: makes --changed a subset PARSE, not just a subset
# report. A full run banks per-file content hashes plus the file-level
# reverse-edge map of the call graph; a later --changed run validates
# the hashes of every UNtouched file against it, expands the touched
# set through the cached reverse map, and parses that closure PLUS its
# transitive import closure (plus the registry-declaring files, so
# registry-consistency never reports phantom undeclared uses). Any
# mismatch — absent cache, stale hash, version bump — falls back to
# the full parse, which refreshes the cache. Soundness note: reverse
# dependents are exactly the CALLERS of the touched files,
# transitively, so lockset entry-locksets and chain roots are always
# in the parse set; the import closure covers the CALLEE direction
# (every call mxflow can resolve goes through an import or stays in
# file), so effect summaries reasoned over in subset mode match the
# full run's. The same two directions cover mxsync: a thread ROOT's
# registration site refs its target (the rev map records ref edges
# too, so registration files are reverse dependents), races are
# class-/file-scoped, gate crossings live in callers (reverse
# closure) and collective def-markers in callees (import closure).
# The report is still filtered to touched files + reverse
# dependents — plus any sink whose witness chain crosses one (see
# Finding.via).

DEP_CACHE_VERSION = 2


def _text_sha(text):
    return hashlib.sha1(
        text.encode("utf-8", "surrogatepass")).hexdigest()


def _registry_decl_files(project):
    """Files declaring a string registry (top-level ``SITES`` /
    ``FUSED_FALLBACK_CODES`` / ``COUNTERS``) — always parsed in
    dep-cache subset mode."""
    names = {"SITES", "FUSED_FALLBACK_CODES", "COUNTERS"}
    out = set()
    for src in project.sources:
        for node in src.tree.body:
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target]
                       if isinstance(node, ast.AnnAssign) else ())
            if any(isinstance(t, ast.Name) and t.id in names
                   for t in targets):
                out.add(src.display)
                break
    return sorted(out)


def _file_rev_map(graph):
    """File-level reverse edges of the call graph: {callee file ->
    caller files}, cross-file edges only. ONE implementation shared by
    the cache writer and the in-memory expansion so the cache-hit and
    cache-miss paths can never drift apart."""
    rev = {}
    for fi, edges in graph._edges.items():
        for callee, _line, _col, _kind in edges:
            if callee.src.display != fi.src.display:
                rev.setdefault(callee.src.display,
                               set()).add(fi.src.display)
    return rev


def _grow_closure(seed, edge_map):
    """Expand ``seed`` (a set, mutated in place) by BFS over
    ``edge_map`` (node -> iterable of neighbours)."""
    queue = list(seed)
    while queue:
        d = queue.pop()
        for dep in edge_map.get(d, ()):
            if dep not in seed:
                seed.add(dep)
                queue.append(dep)


def _parse_import_closure(project, files, display_fn):
    """Grow a subset parse through its imports, transitively: effect
    facts flow CALLEE-ward (a hot caller's blocking sink lives in the
    helper file it calls into; a donation summary comes from the
    builder a touched caller binds), and every call mxflow can resolve
    crosses files only through an import — so closing the parse set
    over in-scan-set imports restores the facts subset mode reasons
    with. Touched files use their FRESH imports (just parsed), so a
    newly added dependency is followed even though the dep cache
    predates it."""
    from . import callgraph as _cg
    index = {}
    for path in files:
        d = display_fn(path)
        index.setdefault(_cg.module_name_of(d), (d, path))
    parsed = {s.display for s in project.sources}
    queue = list(project.sources)
    while queue:
        src = queue.pop()
        for origin in set(_cg._import_map(src).values()):
            parts = origin.split(".")
            for cut in range(len(parts), 0, -1):
                hit = index.get(".".join(parts[:cut]))
                if hit is None:
                    continue
                d, path = hit
                if d not in parsed:
                    parsed.add(d)
                    nsrc = project.add_file(path)
                    if nsrc is not None:
                        queue.append(nsrc)
                break


def write_dep_cache(path, project, paths=(), force=False):
    """Bank the dependency skeleton of a full-view run (best-effort:
    returns False without raising when the graph was never built or the
    write fails — the cache is an accelerator, never a requirement).

    ``paths``: the normalized lint-path set the skeleton covers — a
    later ``--changed`` run over a DIFFERENT path set must not trust
    it. Unless ``force``, an existing cache covering a different path
    set is left alone: a one-off narrow run (a fixture test, a single
    file) must not clobber the developer's repo-wide pre-commit
    accelerator. A --changed fallback passes ``force`` — its path set
    is the canonical consumer, so it wins."""
    graph = project._graph
    if graph is None:
        return False
    paths = sorted(paths)
    if not force:
        existing = load_dep_cache(path)
        if existing is not None and existing.get("paths") != paths:
            return False
    rev = _file_rev_map(graph)
    doc = {
        "version": DEP_CACHE_VERSION,
        "paths": paths,
        "files": {s.display: _text_sha(s.text)
                  for s in project.sources},
        "rev": {k: sorted(v) for k, v in sorted(rev.items())},
        "registry_files": _registry_decl_files(project),
    }
    tmp = "%s.%d.tmp" % (path, os.getpid())
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def load_dep_cache(path):
    """The parsed cache document, or None on any problem (absent,
    unreadable, wrong version, malformed) — the caller falls back to a
    full parse either way."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) \
            or doc.get("version") != DEP_CACHE_VERSION \
            or not isinstance(doc.get("files"), dict) \
            or not isinstance(doc.get("rev"), dict):
        return None
    return doc


class Baseline:
    """The committed grandfather file: findings listed here report as
    ``baselined`` (exit 0) instead of failing the run.

    Entries are ``{rule, path, anchor, count}``; identity is
    :meth:`Finding.key`. The loader TOLERATES entries that no longer
    match any current finding — they surface as ``stale`` warnings and
    are pruned by ``--update-baseline``, never an error (deleting the
    offending code must not break the lint that flagged it)."""

    def __init__(self, path=None):
        self.path = path
        self.entries = {}        # key -> allowed count
        self.load_warnings = []

    @classmethod
    def load(cls, path):
        bl = cls(path)
        if path is None or not os.path.exists(path):
            return bl
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            bl.load_warnings.append("baseline %s unreadable (%s) — "
                                    "running without it" % (path, e))
            return bl
        items = data.get("findings", []) if isinstance(data, dict) else []
        for ent in items:
            if not isinstance(ent, dict):
                bl.load_warnings.append(
                    "baseline entry %r is not an object — skipped" % (ent,))
                continue
            try:
                key = (str(ent["rule"]), str(ent["path"]),
                       str(ent["anchor"]))
                count = max(1, int(ent.get("count", 1)))
            except KeyError as e:
                bl.load_warnings.append(
                    "baseline entry missing field %s — skipped" % e)
                continue
            except (TypeError, ValueError):
                bl.load_warnings.append(
                    "baseline entry %r has a non-integer count — "
                    "counted as 1" % (ent.get("anchor"),))
                count = 1
            bl.entries[key] = bl.entries.get(key, 0) + count
        return bl

    def partition(self, findings):
        """(kept, baselined, stale) — ``kept`` are findings the baseline
        does not cover; ``stale`` are baseline entries with no matching
        current finding (candidates for pruning)."""
        remaining = dict(self.entries)
        kept, baselined = [], []
        for f in findings:
            k = f.key()
            if f.rule in NEVER_BASELINED:
                kept.append(f)
            elif remaining.get(k, 0) > 0:
                remaining[k] -= 1
                baselined.append(f)
            else:
                kept.append(f)
        stale = [{"rule": r, "path": p, "anchor": a, "count": n}
                 for (r, p, a), n in sorted(remaining.items()) if n > 0]
        return kept, baselined, stale

    @staticmethod
    def render(findings):
        """The JSON document ``--update-baseline`` writes: every CURRENT
        unsuppressed finding, stale entries implicitly pruned.
        :data:`NEVER_BASELINED` rules are excluded — they must keep
        failing the gate until the code is fixed."""
        counts = {}
        for f in findings:
            if f.rule in NEVER_BASELINED:
                continue
            counts[f.key()] = counts.get(f.key(), 0) + 1
        return {
            "version": 1,
            "comment": "grandfathered mxlint findings; regenerate with "
                       "tools/mxlint.py --update-baseline <paths>",
            "findings": [
                {"rule": r, "path": p, "anchor": a, "count": n}
                for (r, p, a), n in sorted(counts.items())],
        }


class Report:
    """One run's outcome: what fails the gate (``findings``), what was
    silenced and why (``suppressed``/``baselined``), and the baseline
    hygiene warnings (``stale_baseline``)."""

    def __init__(self, findings, suppressed, baselined, stale_baseline,
                 warnings, paths, rules, timings=None, callgraph=None,
                 files=0, subset=None, dep_cache=None, closure=None):
        self.findings = findings
        self.suppressed = suppressed      # [(finding, justification)]
        self.baselined = baselined
        self.stale_baseline = stale_baseline
        self.warnings = warnings
        self.paths = paths
        self.rules = rules
        self.timings = dict(timings or {})    # rule/pass -> seconds
        self.callgraph = dict(callgraph or {})  # graph + cache stats
        self.files = files
        self.subset = subset            # --changed: files actually linted
        self.dep_cache = dep_cache      # None | "hit" | "miss:<why>"
        self.closure = closure          # --changed: what was linted, audited

    @property
    def clean(self):
        return not self.findings

    def counts(self):
        out = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self):
        return {
            "version": 1,
            "paths": list(self.paths),
            "rules": list(self.rules),
            "clean": self.clean,
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [dict(f.to_dict(), justification=j)
                           for f, j in self.suppressed],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "warnings": list(self.warnings),
            "files": self.files,
            "timings": {k: round(v, 4)
                        for k, v in sorted(self.timings.items())},
            "callgraph": self.callgraph,
            "subset": self.subset,
            "dep_cache": self.dep_cache,
            "closure": self.closure,
        }

    def render_text(self):
        lines = []
        for w in self.warnings:
            lines.append("warning: %s" % w)
        for ent in self.stale_baseline:
            lines.append(
                "warning: stale baseline entry (no longer found): "
                "%(rule)s %(path)s %(anchor)r — prune with "
                "--update-baseline" % ent)
        for f in self.findings:
            lines.append(f.render())
        lines.append(
            "mxlint: %d finding(s), %d suppressed, %d baselined, "
            "%d stale baseline entr%s"
            % (len(self.findings), len(self.suppressed),
               len(self.baselined), len(self.stale_baseline),
               "y" if len(self.stale_baseline) == 1 else "ies"))
        return "\n".join(lines)


def _load_rules(rule_ids=None):
    from . import rules as _rules
    table = _rules.rule_table()
    ids = list(rule_ids) if rule_ids else list(ALL_RULE_IDS)
    unknown = [r for r in ids if r not in table]
    if unknown:
        raise ValueError("unknown rule id(s): %s (known: %s)"
                         % (", ".join(unknown), ", ".join(table)))
    return [(rid, table[rid]) for rid in ids]


def _timed_check(timings, rid, project, raw, thunk):
    """Run one rule pass, charging its wall time to ``rid`` MINUS any
    callgraph/summaries build it lazily triggered (those are reported
    under their own keys — without the subtraction the first
    interprocedural rule to run would double-count the whole build)."""
    t0 = time.perf_counter()
    build_before = sum(project.timings.values())
    raw.extend(thunk())
    spent = (time.perf_counter() - t0) \
        - (sum(project.timings.values()) - build_before)
    timings[rid] = timings.get(rid, 0.0) + max(spent, 0.0)


def run(paths, rules=None, baseline=None, root=None, only=None,
        expand_dependents=False, dep_cache=None):
    """Analyze ``paths`` (files/dirs) with the given rule ids (default:
    all) against ``baseline`` (a path, a :class:`Baseline`, or None).
    Returns a :class:`Report`. ``root`` rebases display paths (the CLI
    passes the repo root so baseline entries stay machine-independent).

    ``only`` (``--changed`` mode): an iterable of display paths — per-
    file rules run only on the subset and every reported finding is
    filtered to it, except that a chain-bearing finding whose witness
    chain crosses the subset is kept even when its sink anchors
    elsewhere. With ``expand_dependents`` the subset grows by the
    transitive REVERSE call-graph closure (files with a call edge into
    a changed file): a changed callee changes its callers' effect
    summaries, so their findings can change too. Stale-baseline hygiene
    is skipped in subset mode: entries covering the unscanned remainder
    would all read as stale.

    ``dep_cache`` (a path): a full-view run banks the dependency
    skeleton there; a subset run that validates against it parses ONLY
    the expanded closure plus its transitive import closure (the
    callee direction — summaries need real callee bodies) plus the
    registry-declaring files, instead of the whole path set — the
    fast pre-commit loop. Falls back to the full parse (and refreshes
    the cache) on any mismatch.
    """
    files = iter_python_files(paths)

    def _display(path):
        d = os.path.relpath(path, root) if root else path
        return d.replace(os.sep, "/")

    only_set = None
    cache_state = None
    parse_only = None           # set of displays to parse (fast path)
    norm_paths = sorted(_display(p) for p in paths)
    if only is not None:
        only_set = {p.replace(os.sep, "/") for p in only}
        if expand_dependents and only_set and dep_cache:
            cache = load_dep_cache(dep_cache)
            if cache is None:
                cache_state = "miss:absent"
            elif cache.get("paths") != norm_paths:
                # skeleton banked for a different lint-path set: its
                # rev map may be missing whole directories
                cache_state = "miss:paths"
            elif only_set & set(cache.get("registry_files", ())):
                # a registry-DECLARING file was touched: its uses live
                # anywhere in the scan set with no call edge to follow
                # (registry consistency is string-keyed, not called),
                # so only the full parse can re-check every use site
                cache_state = "miss:registry-decl-touched"
            else:
                stale = None
                for path in files:
                    d = _display(path)
                    if d in only_set:
                        continue        # touched files may differ freely
                    want = cache["files"].get(d)
                    if want is None:
                        stale = d
                        break
                    try:
                        with open(path, encoding="utf-8") as f:
                            if _text_sha(f.read()) != want:
                                stale = d
                                break
                    except OSError:
                        stale = d
                        break
                if stale is not None:
                    cache_state = "miss:stale"
                else:
                    # unchanged files match the cache exactly, so the
                    # cached reverse map is valid for them — and edges
                    # FROM touched files only ever point callee-ward,
                    # which the callers-only closure never follows
                    _grow_closure(only_set, cache["rev"])
                    parse_only = only_set \
                        | set(cache.get("registry_files", ()))
                    cache_state = "hit"

    project = Project(root=root)
    for path in files:
        if parse_only is not None and _display(path) not in parse_only:
            continue
        project.add_file(path)
    if parse_only is not None:
        # the reverse closure restored the CALLERS; now restore the
        # CALLEES — without them, summaries for touched functions are
        # computed against thin air and interprocedural findings
        # anchored in (or chained through) touched files are missed
        _parse_import_closure(project, files, _display)

    if only_set is not None and expand_dependents and parse_only is None:
        _grow_closure(only_set, _file_rev_map(project.callgraph()))

    selected = _load_rules(rules)
    timings = {}
    raw = list(project.parse_errors)
    for src in project.sources:
        raw.extend(src.grammar_findings)
        if only_set is not None and src.display not in only_set:
            continue
        for rid, rule in selected:
            check = getattr(rule, "check_source", None)
            if check is not None:
                _timed_check(timings, rid, project, raw,
                             lambda: check(src, project))
    for rid, rule in selected:
        check = getattr(rule, "check_project", None)
        if check is not None:
            _timed_check(timings, rid, project, raw,
                         lambda: check(project))

    via_kept = 0
    if only_set is not None:
        # keep a finding when it is anchored in the subset OR its
        # witness chain crosses it: a hot loop edited to call into an
        # existing helper sinks in the UNtouched helper file, and that
        # is precisely the regression --changed exists to catch
        kept_raw = []
        for f in raw:
            if f.path in only_set:
                kept_raw.append(f)
            elif any(v in only_set for v in f.via):
                kept_raw.append(f)
                via_kept += 1
        raw = kept_raw
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    by_display = {s.display: s for s in project.sources}
    unsuppressed, suppressed = [], []
    for f in raw:
        src = by_display.get(f.path)
        just = None
        if src is not None and f.rule != SUPPRESSION_RULE:
            just = src.suppressed(f.rule, f.line)
        if just is not None:
            suppressed.append((f, just))
        else:
            unsuppressed.append(f)

    if baseline is None or isinstance(baseline, Baseline):
        bl = baseline or Baseline()
    else:
        bl = Baseline.load(baseline)
    kept, baselined, stale = bl.partition(unsuppressed)
    if only_set is not None:
        stale = []
    timings.update(project.timings)
    stats = {}
    if project._graph is not None:
        stats = project._graph.stats()
        from . import summaries as _summaries
        stats["facts_cache"] = _summaries.cache_stats()
        stats.update(project.extra_stats)   # mxsync model stats
    if dep_cache and parse_only is None and project._graph is not None:
        # this run parsed the full path set and built the graph —
        # refresh the skeleton so the next --changed run goes fast.
        # A --changed fallback forces: its path set is the canonical
        # consumer; a plain narrow run never clobbers a cache covering
        # a different path set
        write_dep_cache(dep_cache, project, paths=norm_paths,
                        force=only is not None)
    closure = None
    if only_set is not None:
        # the audit record for a "0 findings" on a partial view: what
        # was touched, what the reverse closure expanded it to, what
        # was actually parsed, and how many sink-elsewhere findings
        # only survived because their witness chain crossed the subset
        touched = sorted({p.replace(os.sep, "/") for p in only})
        closure = {
            "touched": touched,
            "linted": sorted(only_set),
            "dependents": len(only_set) - len(set(touched) & only_set),
            "parsed": sorted(s.display for s in project.sources),
            "via_kept": via_kept,
        }
    return Report(kept, suppressed, baselined, stale,
                  list(bl.load_warnings),
                  [p.replace(os.sep, "/") for p in paths],
                  [rid for rid, _ in selected],
                  timings=timings, callgraph=stats,
                  files=len(project.sources),
                  subset=sorted(only_set) if only_set is not None
                  else None,
                  dep_cache=cache_state, closure=closure)
