"""lock-discipline: annotated shared state is only touched under its lock.

The threaded layers (serving coalescer/resolver pool, the process-global
telemetry registry, the compile cache, fault registry and checkpoint
manager) guard shared state with ``threading.Lock`` objects — a
convention nothing checked until now, and exactly the class of bug that
shipped (and had to be hotfixed) in PR 4's ledger finalizer. The
annotation grammar::

    self._stats = Counter()        # guarded by: self._lock
    _counters = {}                 # guarded by: _lock

declares that every later read or write of that attribute (within its
class) or module global (within its module) must sit lexically inside
``with <lock>:`` — where ``<lock>`` is the annotated expression or a
``threading.Condition`` constructed over it (``self._space =
threading.Condition(self._lock)`` makes ``with self._space:`` count).

Exemptions the checker grants (everything else needs a justified
``# mxlint: disable=lock-discipline -- why``):

* ``__init__`` methods / module top level — construction happens-before
  publication to other threads;
* functions whose name ends ``_locked`` — the documented
  caller-holds-the-lock convention (``telemetry._ledger_drain_locked``);
* for globals, functions where the name is a plain local (no ``global``
  declaration) — that's a different variable.

Plus the finalizer check: a callback handed to ``weakref.finalize``
must NOT acquire any known lock — cyclic GC can run finalizers
synchronously on a thread that already holds it (any allocation inside
a locked section can trip the GC threshold), deadlocking the process;
the PR 4 ledger hotfix is the in-repo precedent. Flagged on the
``with``/``.acquire()`` inside the callback.
"""
import ast

from ..core import expr_text, is_self_attr

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition"}


class _Access:
    __slots__ = ("node", "funcs", "classes", "withs", "is_store")

    def __init__(self, node, funcs, classes, withs, is_store):
        self.node = node
        self.funcs = funcs          # tuple of enclosing FunctionDef nodes
        self.classes = classes      # tuple of enclosing ClassDef nodes
        self.withs = withs          # frozenset of canonical lock texts
        self.is_store = is_store


class _Walker(ast.NodeVisitor):
    """One pass over the tree collecting every Name/Attribute access
    with its enclosing (function, class, with-lock) context."""

    def __init__(self, canonical):
        self.canonical = canonical  # with-expr text -> canonical lock
        self.funcs = []
        self.classes = []
        self.withs = []
        self.accesses = []
        self.finalize_calls = []    # (Call node, funcs snapshot)

    def _snap(self, node, is_store):
        self.accesses.append(_Access(
            node, tuple(self.funcs), tuple(self.classes),
            frozenset(self.withs), is_store))

    def visit_FunctionDef(self, node):
        # decorators/defaults evaluate at def time (under any held
        # lock); the BODY runs later, without it — a callback defined
        # inside `with lock:` and handed to a pool/finalizer must not
        # inherit the lock context (the deferred-callback bug class)
        for dec in node.decorator_list:
            self.visit(dec)
        self.visit(node.args)
        self.funcs.append(node)
        held, self.withs = self.withs, []
        for stmt in node.body:
            self.visit(stmt)
        self.withs = held
        self.funcs.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.visit(node.args)
        held, self.withs = self.withs, []
        self.visit(node.body)
        self.withs = held

    def visit_ClassDef(self, node):
        self.classes.append(node)
        self.generic_visit(node)
        self.classes.pop()

    def visit_With(self, node):
        # the with-items themselves evaluate BEFORE the lock is held
        held = []
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            text = expr_text(item.context_expr)
            held.append(self.canonical.get(text, text))
        self.withs.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        del self.withs[len(self.withs) - len(held):]

    visit_AsyncWith = visit_With

    def visit_Name(self, node):
        self._snap(node, isinstance(node.ctx, (ast.Store, ast.Del)))

    def visit_Attribute(self, node):
        self._snap(node, isinstance(node.ctx, (ast.Store, ast.Del)))
        self.visit(node.value)

    def visit_Call(self, node):
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name == "finalize" and len(node.args) >= 2:
            self.finalize_calls.append((node, tuple(self.funcs)))
        self.generic_visit(node)


class LockDisciplineRule:
    id = "lock-discipline"
    fixture_basenames = ("lock_discipline_violation.py", "lock_discipline_ok.py")

    def check_source(self, src, project):
        # cheap precondition: locks (and Condition aliases) cannot exist
        # without the word "threading" somewhere in the file — skip the
        # full access walk for the ~90% of files without it
        if "threading" not in src.text and not src.guards:
            return []
        aliases = src.import_aliases()
        parents = src.parents()

        def owner_class(node):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    return cur
                cur = parents.get(cur)
            return None

        # -- pass 1: known locks + Condition aliasing -----------------------
        # canonical: with-expr text -> the underlying lock's text
        canonical = {}
        known_locks = set()          # texts: "_lock", "self._lock", ...
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            origin = src.resolve(node.value.func, aliases)
            if origin not in _LOCK_FACTORIES:
                continue
            target = node.targets[0]
            if isinstance(target, ast.Name) or is_self_attr(target):
                text = expr_text(target)
                known_locks.add(text)
                canonical.setdefault(text, text)
                if origin.endswith("Condition") and node.value.args:
                    inner = expr_text(node.value.args[0])
                    if inner:
                        canonical[text] = inner
                        known_locks.add(inner)

        # -- pass 2: guard annotations -> entities --------------------------
        # entity: ("global"|"attr", name, owner ClassDef or None, lock,
        #          annotation line)
        entities = []
        findings = []
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                continue
            lock = src.guards.get(node.lineno)
            if lock is None:
                continue
            lock = canonical.get(lock, lock)
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            matched = False
            for t in targets:
                if isinstance(t, ast.Name) and owner_class(node) is None \
                        and not any(isinstance(p, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef))
                                    for p in _ancestors(node, parents)):
                    entities.append(("global", t.id, None, lock,
                                     node.lineno))
                    matched = True
                elif is_self_attr(t):
                    entities.append(("attr", t.attr, owner_class(node),
                                     lock, node.lineno))
                    matched = True
            if not matched:
                findings.append(src.finding(
                    self.id, node,
                    "'# guarded by:' annotation on an unsupported "
                    "target — annotate a module-global or self.<attr> "
                    "assignment"))

        if not entities and not known_locks:
            return findings

        # -- pass 3: every access, with context -----------------------------
        walker = _Walker(canonical)
        walker.visit(src.tree)

        func_locals = {}

        def locals_of(fn):
            got = func_locals.get(fn)
            if got is None:
                assigned, declared_global = set(), set()
                for n in ast.walk(fn):
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, (ast.Store, ast.Del)):
                        assigned.add(n.id)
                    elif isinstance(n, (ast.Global, ast.Nonlocal)):
                        declared_global.update(n.names)
                    elif isinstance(n, ast.arg):
                        assigned.add(n.arg)
                got = func_locals[fn] = assigned - declared_global
            return got

        def exempt(acc):
            if not acc.funcs:
                return True                      # module level / class body
            # INNERMOST function only: a closure defined inside
            # __init__/*_locked but handed to a pool or finalizer runs
            # later, outside both the constructor and the lock — it
            # earns no exemption from its definition site
            inner = acc.funcs[-1]
            return inner.name == "__init__" \
                or inner.name.endswith("_locked")

        seen_lines = set()
        for kind, name, owner, lock, _ann_line in entities:
            for acc in walker.accesses:
                node = acc.node
                if kind == "global":
                    if not (isinstance(node, ast.Name) and node.id == name):
                        continue
                    if not acc.funcs:
                        continue                 # module-level init
                    inner = acc.funcs[-1]
                    if name in locals_of(inner):
                        continue                 # a plain local shadows it
                elif kind == "attr":
                    if not is_self_attr(node, name):
                        continue
                    if owner is not None and owner not in acc.classes:
                        continue                 # another class's attr
                if exempt(acc):
                    continue
                if lock in acc.withs:
                    continue
                where = acc.funcs[-1].name if acc.funcs else "<module>"
                dedup = (name, node.lineno, node.col_offset)
                if dedup in seen_lines:
                    continue
                seen_lines.add(dedup)
                label = "attribute 'self.%s'" % name if kind == "attr" \
                    else "global '%s'" % name
                findings.append(src.finding(
                    self.id, node,
                    "%s is annotated '# guarded by: %s' but is %s "
                    "outside a with-block on that lock (in %s)"
                    % (label, lock,
                       "written" if acc.is_store else "read", where)))

        # -- pass 4: weakref.finalize callbacks must not take a lock --------
        findings.extend(self._check_finalizers(
            src, walker, aliases, known_locks, canonical))
        return findings

    def _check_finalizers(self, src, walker, aliases, known_locks,
                          canonical):
        findings = []
        module_funcs = {n.name: n for n in src.tree.body
                        if isinstance(n, ast.FunctionDef)}
        method_index = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        method_index[item.name] = item

        for call, _funcs in walker.finalize_calls:
            origin = src.resolve(call.func, aliases)
            if origin != "weakref.finalize" and not (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "finalize"
                    and origin is None):
                continue
            cb = call.args[1]
            body = None
            label = expr_text(cb)
            if isinstance(cb, ast.Lambda):
                body, label = cb, "<lambda>"
            elif isinstance(cb, ast.Name):
                body = module_funcs.get(cb.id)
            elif is_self_attr(cb):
                body = method_index.get(cb.attr)
            if body is None:
                continue
            for n in ast.walk(body):
                bad = None
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        text = expr_text(item.context_expr)
                        if canonical.get(text, text) in known_locks:
                            bad = "with %s" % text
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "acquire":
                    text = expr_text(n.func.value)
                    if canonical.get(text, text) in known_locks:
                        bad = "%s.acquire()" % text
                if bad:
                    findings.append(src.finding(
                        self.id, n,
                        "lock acquisition (%s) inside weakref.finalize "
                        "callback '%s' — cyclic GC can run finalizers on "
                        "a thread already holding the lock and deadlock "
                        "the process (the PR 4 ledger bug); hand the "
                        "work to a lock-free pending queue drained under "
                        "the lock instead" % (bad, label)))
        return findings


def _ancestors(node, parents):
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)
