"""host-sync: hot paths must not block on device values.

A ``.asnumpy()`` / ``.wait_to_read()`` / ``np.asarray(...)`` on a
device value stalls the dispatch pipeline until the device (often a
REMOTED PJRT backend, a network round-trip away) catches up — the exact
stall class that hid the 14x ``Module.fit`` gap until round 5
(PERF.md). Functions marked ``# mxlint: hot`` (the fit batch loop, the
serving coalescer/launch/dispatch paths) are checked for all three
forms; everything in them must stay async, with blocking fetches pushed
to epoch boundaries, lazy metric flushes or the resolver pool.

``np.asarray`` over an obvious host literal (list/tuple/dict display,
comprehension, constant) is exempt — building a feed array from Python
scalars is host work, not a device sync. Any remaining legitimate site
(e.g. marshalling a client payload on the serving admission path)
carries a justified ``# mxlint: disable=host-sync -- why``.
"""
import ast

_BLOCKING_METHODS = {"asnumpy", "wait_to_read"}
_HOST_LITERALS = (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.ListComp,
                  ast.SetComp, ast.DictComp, ast.GeneratorExp,
                  ast.Constant)


class HostSyncRule:
    id = "host-sync"

    def _hot_functions(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            # a standalone marker above a DECORATED def arms the first
            # decorator's line, not the `def` line — accept either so
            # the marker is never silently inert
            lines = {node.lineno}
            if node.decorator_list:
                lines.add(min(d.lineno for d in node.decorator_list))
            if lines & src.hot_lines:
                yield node

    def check_source(self, src, project):
        if not src.hot_lines:
            return []
        aliases = src.import_aliases()
        np_names = {local for local, origin in aliases.items()
                    if origin == "numpy"}
        asarray_names = {local for local, origin in aliases.items()
                         if origin == "numpy.asarray"}
        findings = []
        seen = set()
        for fn in self._hot_functions(src):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                msg = None
                if isinstance(f, ast.Attribute) \
                        and f.attr in _BLOCKING_METHODS:
                    msg = ".%s()" % f.attr
                elif ((isinstance(f, ast.Attribute)
                       and f.attr == "asarray"
                       and isinstance(f.value, ast.Name)
                       and f.value.id in np_names)
                      or (isinstance(f, ast.Name)
                          and f.id in asarray_names)):
                    if node.args and isinstance(node.args[0],
                                                _HOST_LITERALS):
                        continue
                    msg = "np.asarray(...)"
                if msg is None:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(src.finding(
                    self.id, node,
                    "blocking host sync %s inside hot function '%s' "
                    "(# mxlint: hot) — this stalls the dispatch "
                    "pipeline on the device; fetch lazily or move the "
                    "sync off the hot path" % (msg, fn.name)))
        return findings
