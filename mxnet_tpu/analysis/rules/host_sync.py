"""host-sync: hot paths must not block on device values.

A ``.asnumpy()`` / ``.wait_to_read()`` / ``np.asarray(...)`` on a
device value stalls the dispatch pipeline until the device (often a
REMOTED PJRT backend, a network round-trip away) catches up — the exact
stall class that hid the 14x ``Module.fit`` gap until round 5
(PERF.md). Functions marked ``# mxlint: hot`` (the fit batch loop, the
serving coalescer/launch/dispatch paths) are checked for all three
forms; everything in them must stay async, with blocking fetches pushed
to epoch boundaries, lazy metric flushes or the resolver pool.

``np.asarray`` over an obvious host literal (list/tuple/dict display,
comprehension, constant) is exempt — building a feed array from Python
scalars is host work, not a device sync. Any remaining legitimate site
(e.g. marshalling a client payload on the serving admission path)
carries a justified ``# mxlint: disable=host-sync -- why``.

Since ISSUE 9 the rule is TRANSITIVE (the mxflow layer): a hot
function that reaches a blocking fetch through any chain of resolved
calls is flagged too, with the chain printed in the finding. The
finding anchors at the SINK line (where the fetch actually is) in the
sink's file — that is where the fix or the justified disable belongs,
and the baseline keys on it, so refactoring an intermediate caller
never invalidates a grandfathered entry. Only ``call`` edges are
traversed: a callback handed to the resolver pool blocks on its own
thread, legally. Dynamic calls are not traversed (bounded).
"""
import ast

from ..callgraph import _walk_same_scope
from ..core import Finding
from ..summaries import classify_sync_call


def _is_hot(node, src):
    """Whether a def is # mxlint: hot-marked. A standalone marker
    above a DECORATED def arms the first decorator's line, not the
    `def` line — accept either so the marker is never silently
    inert."""
    lines = {node.lineno}
    if node.decorator_list:
        lines.add(min(d.lineno for d in node.decorator_list))
    return bool(lines & src.hot_lines)


class HostSyncRule:
    id = "host-sync"
    fixture_basenames = ("host_sync_violation.py", "host_sync_ok.py",
                         "host_sync_chain_violation.py",
                         "host_sync_chain_ok.py")

    def _hot_functions(self, src):
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and _is_hot(node, src):
                yield node

    def check_source(self, src, project):
        if not src.hot_lines:
            return []
        aliases = src.import_aliases()
        np_names = {local for local, origin in aliases.items()
                    if origin == "numpy"}
        asarray_names = {local for local, origin in aliases.items()
                         if origin == "numpy.asarray"}
        findings = []
        seen = set()
        for fn in self._hot_functions(src):
            # a local binding (param, store, nested def name) in the
            # hot function's OWN scope shadowing `np`/`asarray` means
            # calls through it are NOT numpy. Same-scope walk only: a
            # name bound inside a NESTED def shadows nothing out here
            locals_ = set()
            for n in _walk_same_scope(fn):
                if isinstance(n, ast.arg):
                    locals_.add(n.arg)
                elif isinstance(n, ast.Name) \
                        and isinstance(n.ctx, (ast.Store, ast.Del)):
                    locals_.add(n.id)
                elif isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) and n is not fn:
                    locals_.add(n.name)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = classify_sync_call(node, np_names - locals_,
                                         asarray_names - locals_)
                if msg is None:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(src.finding(
                    self.id, node,
                    "blocking host sync %s inside hot function '%s' "
                    "(# mxlint: hot) — this stalls the dispatch "
                    "pipeline on the device; fetch lazily or move the "
                    "sync off the hot path" % (msg, fn.name)))
        findings.extend(self._check_transitive(src, project))
        return findings

    def _check_transitive(self, src, project):
        """Hot functions reaching a blocking fetch through callees —
        anchored at the SINK, chain in the message."""
        graph = project.callgraph()
        summ = project.summaries()
        findings = []
        seen = set()
        for fn in self._hot_functions(src):
            fi = graph.func_for_node(src, fn)
            if fi is None:
                continue
            for callee, line, _col in graph.callees(fi):
                # a justified disable on the CALL LINE in the hot
                # function cuts the chain there ("this call is allowed
                # to block" — e.g. the opt-in divergence probe)
                if src.suppressed(self.id, line) is not None:
                    continue
                # EVERY reachable sink function and EVERY sync site in
                # it gets its own finding (suppression is per line): a
                # justified disable on one fetch must not hide the
                # unjustified one on the next line, or a farther sink
                for chain, sink_fi, sites in summ.sync_witnesses(
                        callee):
                    # a hot-marked sink already gets the direct finding
                    # (same def-or-decorator-line check as
                    # _hot_functions, or a decorator-armed marker
                    # would duplicate the finding at the sink line)
                    if _is_hot(sink_fi.node, sink_fi.src):
                        continue
                    hops = ["%s (%s:%d)" % (fn.name, src.display,
                                            fn.lineno)]
                    via = {src.display}
                    prev = fi
                    for nxt, call_line in [(callee, line)] + chain:
                        hops.append("%s (called at %s:%d)"
                                    % (nxt.name, prev.src.display,
                                       call_line))
                        via.add(nxt.src.display)
                        prev = nxt
                    for sink_line, form in sites:
                        key = (fi, sink_fi.src.display, sink_line)
                        if key in seen:
                            continue
                        seen.add(key)
                        findings.append(Finding(
                            self.id, sink_fi.src.display, sink_line, 0,
                            "blocking host sync %s in '%s' is "
                            "reachable from hot function '%s' "
                            "(# mxlint: hot) through the call chain "
                            "%s — this stalls the dispatch pipeline "
                            "on the device; fetch lazily or move "
                            "the sync off the hot path"
                            % (form, sink_fi.name, fn.name,
                               " -> ".join(hops)),
                            anchor=sink_fi.src.anchor_for(sink_line),
                            via=sorted(via)))
        return findings
