"""collective-discipline: SPMD collective hygiene (mxsync family b).

Three finding shapes over the collective model (:mod:`..collectives`):

1. **ungated collective** — a host-level cross-process collective site
   (``KVStore._host_allgather``, a ``# mxsync: collective``-marked
   function like ``spmd.broadcast_from_zero``) reachable along a call
   path with NO ``CollectiveGate.arrive_and_wait()`` crossing before
   it: a peer that died earlier turns the exchange into a cluster
   hang instead of a ``DeadWorkerError``;
2. **channel mismatch** — the path IS gated, but only on the wrong
   channel ("step" gate guarding a "kv" exchange): generations on the
   two channels advance independently, so the crossing proves nothing
   about the peer this exchange is about to wait on;
3. **rank-divergent collective sequence** — a branch whose condition
   derives from the process rank, the wall clock, fault injection or
   the global RNG, and whose arms (including the fallthrough suffix
   for arms that return/raise) reach DIFFERENT collective sequences:
   one rank skips a psum its peers are blocking in — the one-rank-
   skips-a-collective hang class. A deliberately rank-divergent region
   (rank-0-only checkpoint/logging that calls no collective) compares
   equal and is never flagged; one that genuinely diverges carries a
   justified ``# mxlint: disable=collective-discipline -- why``.

``jax.lax`` device collectives live inside compiled programs whose
*dispatch* the gate protects (invisible statically), so they feed
shape 3 only, never shapes 1/2.
"""
from ..core import Finding
from ..collectives import ANY_CHANNEL


class CollectiveDisciplineRule:
    id = "collective-discipline"
    fixture_basenames = ("collective_violation.py", "collective_ok.py")

    def check_project(self, project):
        graph = project.callgraph()
        cm = project.collectives()
        findings = []

        # shapes 1 + 2: gate coverage of host-level sites
        for fi, site, prior in cm.coverage():
            src = fi.src
            hops = cm.ungated_chain(fi, site.channel)
            via = {src.display}
            chain_text = ""
            if hops:
                parts = []
                for caller, line in hops:
                    via.add(caller.src.display)
                    parts.append("%s (%s:%d)" % (caller.name,
                                                 caller.src.display,
                                                 line))
                chain_text = "; reachable ungated from '%s' via %s" % (
                    hops[0][0].name,
                    " -> ".join(parts + [fi.name]))
            prior_real = sorted(p for p in prior if p != ANY_CHANNEL)
            if prior_real:
                msg = ("collective '%s' exchanges on channel '%s' but "
                       "the path only crosses a CollectiveGate on "
                       "channel %s%s — gate generations advance per "
                       "channel, so the wrong-channel crossing proves "
                       "nothing about the peers this exchange will "
                       "wait on; cross the matching-channel gate "
                       "first (or fix the gate's channel)"
                       % (site.kind, site.channel,
                          ", ".join("'%s'" % p for p in prior_real),
                          chain_text))
            else:
                msg = ("cross-process collective '%s' (channel '%s') "
                       "is reachable with NO CollectiveGate crossing "
                       "before it%s — a peer that died earlier turns "
                       "this exchange into a cluster hang instead of "
                       "a DeadWorkerError; cross the matching "
                       "'%s'-channel gate before the exchange, or "
                       "justify with '# mxlint: "
                       "disable=collective-discipline -- why'"
                       % (site.kind, site.channel, chain_text,
                          site.channel))
            findings.append(Finding(
                self.id, src.display, site.line, site.col, msg,
                anchor=src.anchor_for(site.line), via=sorted(via)))

        # shape 3: rank-divergent collective sequences
        for fi in graph.functions:
            if not cm.reach(fi):
                continue
            src = fi.src
            for node, reason, a, b in cm.divergences(fi):
                only_a = sorted(a - b)
                only_b = sorted(b - a)
                findings.append(src.finding(
                    self.id, node,
                    "branch condition in '%s' derives from %s and its "
                    "arms reach DIFFERENT collective sequences "
                    "(if-arm only: %s; else/fallthrough only: %s) — "
                    "a process taking the other arm skips or adds a "
                    "cross-process collective its peers are blocking "
                    "in (cluster hang, not a crash); make the "
                    "collective sequence rank-invariant, or justify a "
                    "deliberately divergent region with '# mxlint: "
                    "disable=collective-discipline -- why'"
                    % (fi.name, reason,
                       ", ".join(only_a) or "(none)",
                       ", ".join(only_b) or "(none)")))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings
