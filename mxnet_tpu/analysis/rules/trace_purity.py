"""trace-purity: code reachable from a traced entry point stays pure.

``jax.jit`` runs a function's Python body ONCE, at trace time, and
bakes whatever it observed into the compiled program. A Python side
effect inside that cone — writing ``self.<attr>`` or a module global,
reading the wall clock or the global RNG, bumping a telemetry counter
— executes once per COMPILE instead of once per step: the counter
undercounts forever, the timestamp freezes, the mutated cache holds a
tracer object. These bugs are invisible at the call site because the
impurity can live three frames below the traced closure.

The rule therefore goes interprocedural (the mxflow layer):

* **roots** — every function the runtime traces: the ``fn`` handed to
  an ``executor._InstrumentedProgram(kind, fn, ...)`` build, the
  grandfathered raw ``jax.jit(fn)`` component kernels, and
  ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorated
  kernels;
* **reachability** — BFS over the call graph, ``call`` AND ``ref``
  edges (a function passed as a value to ``jax.vjp``/
  ``jax.checkpoint`` inside the cone is traced too). Dynamic calls
  are NOT traversed (bounded: the chain in a finding is always a real
  call path);
* **facts** — the per-function effect summaries: nonlocal mutations,
  wall-clock reads, global-RNG draws, telemetry calls.

Findings anchor at the impure STATEMENT (the sink), with the trace
chain from the root printed in the message; the baseline keys on the
sink line only, so refactoring an intermediate caller never
invalidates a grandfathered entry. Deliberately impure trace-time code
(e.g. a build-time cache write that never runs under the tracer)
carries a justified ``# mxlint: disable=trace-purity -- why``.
"""
import ast
from collections import deque

from ..core import Finding
from .. import callgraph as cg
from .jit_site import resolve_jit_target, partial_jit_target

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

_KIND_LABEL = {
    "mutates": "mutates non-local state (%s)",
    "reads-clock": "reads the wall clock (%s)",
    "reads-rng": "draws from the global RNG (%s)",
    "calls-telemetry": "calls telemetry (%s)",
}


def _module_scope_calls(tree):
    """Call nodes that execute at module import time (not inside any
    def — class bodies included, they run at import)."""
    stack = [tree]
    while stack:
        n = stack.pop()
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _FUNC_NODES):
                continue
            if isinstance(child, ast.Call):
                yield child
            stack.append(child)


class TracePurityRule:
    id = "trace-purity"
    fixture_basenames = ("trace_purity_violation.py", "trace_purity_ok.py")

    def _roots(self, project, graph):
        """[(FuncInfo, root description, registration file)] — every
        function whose body the runtime traces into a compiled
        program. The registration file (where the ``jax.jit`` /
        ``_InstrumentedProgram`` call lives) can differ from the root
        function's own file; findings carry it in ``via`` so a
        ``--changed`` run touching only the registration site still
        surfaces the finding."""
        roots = []

        def resolve_arg(src, scope, arg):
            if isinstance(arg, ast.Name):
                got = graph.resolve_name(src, scope, arg.id)
                if got is not None and got[0] == "func":
                    return got[1]
            elif isinstance(arg, ast.Attribute) \
                    and isinstance(arg.value, ast.Name) \
                    and arg.value.id in ("self", "cls") \
                    and scope is not None \
                    and scope.self_class is not None:
                # jax.jit(self._kernel): the bound method is traced
                return graph._lookup_method(scope.self_class, arg.attr)
            return None

        def scan_calls(src, scope, calls):
            aliases = src.import_aliases()
            for call in calls:
                f = call.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                target = None
                if name == "_InstrumentedProgram" and len(call.args) >= 2:
                    target = resolve_arg(src, scope, call.args[1])
                elif resolve_jit_target(src, f, aliases) and call.args:
                    target = resolve_arg(src, scope, call.args[0])
                if target is not None:
                    roots.append((target, "traced at %s:%d"
                                  % (src.display, call.lineno),
                                  src.display))

        for src in project.sources:
            scan_calls(src, None, _module_scope_calls(src.tree))
        for fi in graph.functions:
            src = fi.src
            aliases = src.import_aliases()
            scan_calls(src, fi,
                       (n for n in cg._walk_same_scope(fi.node)
                        if isinstance(n, ast.Call)))
            # decorator forms: the decorated function itself is traced
            for dec in fi.node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if resolve_jit_target(src, target, aliases) or (
                        isinstance(dec, ast.Call)
                        and partial_jit_target(src, dec, aliases)):
                    roots.append((fi, "jit-decorated at %s:%d"
                                  % (src.display, dec.lineno),
                                  src.display))
        return roots

    def check_project(self, project):
        graph = project.callgraph()
        summ = project.summaries()
        roots = self._roots(project, graph)
        if not roots:
            return []

        # BFS over call+ref edges from every root; first reacher wins
        # (shortest chains, SCC-safe)
        pred = {}                        # fi -> (parent fi, call line)
        origin = {}                      # fi -> (root fi, desc, reg file)
        queue = deque()
        for fi, desc, reg in roots:
            if fi not in origin:
                origin[fi] = (fi, desc, reg)
                pred[fi] = None
                queue.append(fi)
        while queue:
            f = queue.popleft()
            for callee, line, _col in graph.callees(
                    f, kinds=(cg.CALL, cg.REF)):
                if callee in origin:
                    continue
                # a justified disable ON THE CALL LINE cuts traversal:
                # "this call does not happen under the tracer" (e.g. a
                # runtime isinstance-Tracer guard) silences the whole
                # subtree with ONE annotation at the guard site
                if f.src.suppressed(self.id, line) is not None:
                    continue
                origin[callee] = origin[f]
                pred[callee] = (f, line)
                queue.append(callee)

        findings = []
        seen = set()
        for fi in origin:
            facts = summ.facts_of(fi)
            for kind, line, desc in facts.impure_facts():
                key = (fi.src.display, line, kind)
                if key in seen:
                    continue
                seen.add(key)
                root_fi, root_desc, reg_file = origin[fi]
                chain = self._chain_text(fi, pred, root_fi)
                # the registration file is part of the witness: a
                # --changed run touching only the jit/build call site
                # must still see this finding
                via = {reg_file}
                cur = fi
                while True:
                    via.add(cur.src.display)
                    nxt = pred.get(cur)
                    if nxt is None:
                        break
                    cur = nxt[0]
                findings.append(Finding(
                    self.id, fi.src.display, line, 0,
                    "'%s' %s inside the trace cone of '%s' (%s)%s — "
                    "a side effect under jax tracing runs once per "
                    "COMPILE, not once per step, freezing a stale "
                    "value into every run of the compiled program; "
                    "hoist it out of the traced function or thread "
                    "the value through as an argument"
                    % (fi.name, _KIND_LABEL[kind] % desc,
                       root_fi.name, root_desc, chain),
                    anchor=fi.src.anchor_for(line),
                    via=sorted(via)))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    def _chain_text(self, fi, pred, root_fi):
        hops = []
        cur = fi
        while pred.get(cur) is not None:
            parent, line = pred[cur]
            hops.append("%s -> %s (%s:%d)"
                        % (parent.name, cur.name,
                           parent.src.display, line))
            cur = parent
        if not hops:
            return ""
        hops.reverse()
        return "; call chain: " + ", ".join(hops)
