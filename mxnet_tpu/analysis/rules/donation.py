"""donation-safety: a donated buffer dies at the call that donates it.

The fused train step, the SPMD sharded step and the fused optimizer
update all pass buffers with ``donate_argnums`` — XLA aliases the input
storage into the outputs, and the Python name still pointing at the old
buffer is a use-after-free that jax only sometimes catches (a deleted-
array error on a good day, silently stale data through the compile
cache on a bad one). The rule tracks, per function:

* names locally bound to a ``jax.jit(..., donate_argnums=(...))``
  result (aliases through plain ``y = x`` assignments follow), called
  in the same scope;
* ``self.<attr>`` bound to such a result anywhere in the class;
* call sites carrying an explicit ``# mxlint: donates 0,1`` marker —
  for donated programs whose construction the analyzer cannot see
  locally (``plan["fn"]`` from the module's fused plan, the
  ``FusedUpdater``'s cached step).

After the donating call's statement, any load of a name (or
``self.<attr>``) that was passed at a donated position is a finding,
until a statement rebinds it; rebinding in the donating statement
itself (``w, s = step(w, s)``) is the idiomatic fix and is clean. A
donating call inside a loop whose body never rebinds the donated name
is flagged too — iteration two donates a dead buffer.

Statement order is source order (control flow is not modelled): a use
in an ``else`` branch the call cannot reach may need a justified
disable — the conservative direction for a buffer-lifetime lint.

Since ISSUE 9 donation facts also propagate INTERPROCEDURALLY (the
mxflow effect summaries): a call to an in-repo function that passes
its parameter on at a donated position (``def fused(w): step(w)``
donates ``w``), or through a name bound from a callee that RETURNS a
donating program (``fn = self._build_step(...); fn(ws, states)``),
donates with no ``# mxlint: donates`` marker — the marker grammar
remains only for callees the analyzer genuinely cannot see (dict
lookups like ``plan["fn"]``, dynamic dispatch).
"""
import ast

from ..core import expr_text, is_self_attr
from .jit_site import resolve_jit_target

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _donate_indices(call):
    """Literal donate_argnums of a jit call, or None when absent/
    dynamic."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if not (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)):
                    return None
                out.append(el.value)
            return tuple(out)
        return None
    return None


def _sub_stmts(stmt):
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.stmt):
            yield child
        elif isinstance(child, (ast.excepthandler,) + (
                (ast.match_case,) if hasattr(ast, "match_case") else ())):
            # handler/case bodies hang off non-stmt wrapper nodes — an
            # except-branch (the serving retry paths) must not be a
            # blind spot for a buffer-lifetime lint
            for s in child.body:
                yield s


def _linear_stmts(body, out):
    """Statements in source order, not descending into nested function/
    class scopes (they execute at another time)."""
    for s in body:
        out.append(s)
        if isinstance(s, _SCOPE_NODES + (ast.ClassDef,)):
            continue
        _linear_stmts(list(_sub_stmts(s)), out)


def _walk_same_scope(node):
    """ast.walk that stops at nested function/class definitions."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _SCOPE_NODES + (ast.ClassDef,)):
                continue
            stack.append(child)


def _direct_nodes(stmt):
    """Expression-level nodes belonging to THIS statement only (nested
    sub-statements appear in the linear list in their own right)."""
    stack = [stmt]
    first = True
    while stack:
        n = stack.pop()
        if not first and isinstance(n, ast.stmt):
            continue
        first = False
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.stmt) \
                    or isinstance(child, _SCOPE_NODES + (ast.ClassDef,)):
                continue
            stack.append(child)


def _loads_stores(stmt, kind, name):
    """(loads, stores) of the tracked entity within one statement."""
    loads, stores = [], []
    for n in _walk_same_scope(stmt):
        if kind == "name" and isinstance(n, ast.Name) and n.id == name:
            (stores if isinstance(n.ctx, (ast.Store, ast.Del))
             else loads).append(n)
        elif kind == "attr" and is_self_attr(n, name):
            (stores if isinstance(n.ctx, (ast.Store, ast.Del))
             else loads).append(n)
    return loads, stores


class DonationRule:
    id = "donation-safety"
    fixture_basenames = ("donation_violation.py", "donation_ok.py",
                         "donation_interproc_violation.py",
                         "donation_interproc_ok.py")

    def check_source(self, src, project):
        # cheap PROJECT-level gate first: donation facts can only
        # originate from a literal donate_argnums or an explicit
        # marker somewhere in the scan — without one, skip the whole
        # callgraph + summaries + donation-fixpoint build (cached on
        # the project: check_source runs once per file)
        possible = getattr(project, "_donation_possible", None)
        if possible is None:
            possible = any("donate_argnums" in s.text or s.donates
                           for s in project.sources)
            project._donation_possible = possible
        if not possible:
            return []
        # interprocedural feed: donated call sites the effect
        # summaries can prove for this file's functions (callee
        # donates its param / callee returns a donating program)
        graph = project.callgraph()
        summ = project.summaries()
        inter_sites = {}                # FunctionDef node -> {(l,c): idx}
        for fi in graph.functions_of(src):
            sites = summ.donated_sites(fi)
            if sites:
                inter_sites[fi.node] = sites
        # cheap per-file precondition: a donating callable in THIS
        # file needs the literal keyword, an explicit marker, or an
        # interprocedurally inferred donated site
        if "donate_argnums" not in src.text and not src.donates \
                and not inter_sites:
            return []
        parents = src.parents()
        aliases = src.import_aliases()

        def enclosing_function(node):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, _SCOPE_NODES):
                    return cur
                cur = parents.get(cur)
            return None

        def enclosing_class(node):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    return cur
                cur = parents.get(cur)
            return None

        # -- donating callables, by scope -----------------------------------
        module_fns = {}                 # name -> indices
        scope_fns = {}                  # FunctionDef -> {name: indices}
        class_fns = {}                  # (ClassDef, attr) -> indices
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            if not resolve_jit_target(src, node.value.func, aliases):
                continue
            idx = _donate_indices(node.value)
            if not idx:
                continue
            target = node.targets[0]
            if isinstance(target, ast.Name):
                fn = enclosing_function(node)
                if fn is None:
                    module_fns[target.id] = idx
                else:
                    scope_fns.setdefault(fn, {})[target.id] = idx
            elif is_self_attr(target):
                cls = enclosing_class(node)
                if cls is not None:
                    class_fns[(cls, target.attr)] = idx

        if not (module_fns or scope_fns or class_fns or src.donates
                or inter_sites):
            return []

        findings = []
        scopes = [(None, src.tree.body)]
        for node in ast.walk(src.tree):
            if isinstance(node, _SCOPE_NODES):
                scopes.append((node, node.body))
        for fn, body in scopes:
            findings.extend(self._check_scope(
                src, fn, body, dict(module_fns), scope_fns.get(fn, {}),
                class_fns, enclosing_class, parents,
                inter_sites.get(fn, {})))
        return findings

    def _check_scope(self, src, fn, body, tracked, local_tracked,
                     class_fns, enclosing_class, parents, inter_sites):
        tracked.update(local_tracked)
        owner = enclosing_class(fn) if fn is not None else None
        stmts = []
        _linear_stmts(body, stmts)

        # alias pass in source order: y = x copies x's donation info
        for s in stmts:
            if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                    and isinstance(s.targets[0], ast.Name) \
                    and isinstance(s.value, ast.Name) \
                    and s.value.id in tracked:
                tracked[s.targets[0].id] = tracked[s.value.id]

        findings = []
        for pos, s in enumerate(stmts):
            for call in (n for n in _direct_nodes(s)
                         if isinstance(n, ast.Call)):
                idx = None
                if call.lineno in src.donates:
                    idx = src.donates[call.lineno]
                elif isinstance(call.func, ast.Name) \
                        and call.func.id in tracked:
                    idx = tracked[call.func.id]
                elif is_self_attr(call.func) and owner is not None:
                    idx = class_fns.get((owner, call.func.attr))
                if not idx:
                    # interprocedural: the effect summaries proved
                    # this call site donating (callee passes its param
                    # on, or the callable came from a function that
                    # returns a donating program)
                    idx = inter_sites.get((call.lineno,
                                           call.col_offset))
                if not idx:
                    continue
                callee = expr_text(call.func)
                for i in idx:
                    if i >= len(call.args):
                        continue
                    arg = call.args[i]
                    if isinstance(arg, ast.Name):
                        kind, name = "name", arg.id
                    elif is_self_attr(arg):
                        kind, name = "attr", arg.attr
                    else:
                        continue
                    findings.extend(self._track_after(
                        src, stmts, pos, s, call, callee, i, kind, name,
                        parents))
        return findings

    def _track_after(self, src, stmts, pos, call_stmt, call, callee,
                     arg_i, kind, name, parents):
        label = "self.%s" % name if kind == "attr" else "'%s'" % name

        # rebound by the donating statement itself (w = step(w)): clean
        _, stores_here = _loads_stores(call_stmt, kind, name)
        if stores_here:
            return []

        # donating call in a loop, name never rebound in the loop body:
        # iteration two donates an already-dead buffer
        cur = parents.get(call)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(cur, (ast.For, ast.While)):
                rebound = any(_loads_stores(b, kind, name)[1]
                              for b in cur.body)
                if not rebound:
                    return [src.finding(
                        self.id, call,
                        "%s is donated to %s (arg %d) inside a loop "
                        "that never rebinds it — the second iteration "
                        "passes an already-donated buffer; rebind the "
                        "result (e.g. unpack the call into %s)"
                        % (label, callee, arg_i, label))]
                break
            cur = parents.get(cur)

        for s in stmts[pos + 1:]:
            loads, stores = _loads_stores(s, kind, name)
            if loads:
                return [src.finding(
                    self.id, loads[0],
                    "%s is used after being passed at donated position "
                    "%d of %s (line %d) — donation invalidates the "
                    "buffer; use the call's result, or rebind %s first"
                    % (label, arg_i, callee, call.lineno, label))]
            if stores:
                return []
        return []
