"""thread-race: cross-thread shared-state races (mxsync family a).

The lockset rule flags INTERNAL inconsistency — an attribute locked on
some paths and bare on others. This rule reports the real thing: a
``self.<attr>`` or module global WRITTEN under one thread root and
read or written under a DIFFERENT root with an empty lockset
intersection. Thread roots come from the static thread model
(:mod:`..threads`): ``threading.Thread``/``Timer`` targets, pool
``submit`` callbacks, HTTP-server handler methods, ``atexit``/signal/
excepthook registrations, ``weakref.finalize`` callbacks — with
*runs-on-roots* propagated through ``call`` AND ``ref`` edges, so a
method the coalescer thread hands onward as a callback still carries
the coalescer's root. The main thread is a root of its own.

An access's effective lockset is the locks held lexically at it plus
the function's ENTRY lockset (the shared RacerD meet in
:func:`..threads.entry_locksets`). Noise control mirrors lockset's:

* attributes/globals already annotated ``# guarded by:`` belong to
  lock-discipline (which enforces every access) and are skipped;
* constructor bodies (``__init__``/``__new__``/``__setstate__``) are
  construction-before-publication; lock/Condition objects, method
  names and ``threading.local()`` globals are not shared state;
* at least one of the two accesses must be a WRITE, and the two must
  be attributable to two DISTINCT roots.

The finding anchors at the racing WRITE, carries BOTH witness chains
(root registration site -> ... -> accessing function) and proposes the
exact ``# guarded by:`` line — after which lock-discipline enforces it
everywhere, forever. Deliberate lock-free fast paths (GIL-atomic deque
appends, monotonic flag reads) carry a justified
``# mxlint: disable=thread-race -- why`` on the write line.
"""
import ast

from ..core import Finding
from ..threads import MAIN_ROOT, entry_locksets
from .lockset import _annotated_attrs

_CTOR_NAMES = ("__init__", "__new__", "__setstate__")


class _Access:
    __slots__ = ("fi", "line", "col", "is_store", "eff", "roots")

    def __init__(self, fi, line, col, is_store, eff, roots):
        self.fi = fi
        self.line = line
        self.col = col
        self.is_store = is_store
        self.eff = eff                  # effective lockset
        self.roots = roots              # frozenset of root ids


def _annotated_globals(src):
    """Module-global names whose top-level assignment carries a
    '# guarded by:' annotation (lock-discipline owns those)."""
    out = set()
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        if node.lineno not in src.guards:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


class ThreadRaceRule:
    id = "thread-race"
    fixture_basenames = ("thread_race_violation.py", "thread_race_ok.py")

    def check_project(self, project):
        graph = project.callgraph()
        summ = project.summaries()
        tm = project.threads()
        if not tm.roots:
            return []
        findings = []
        findings.extend(self._check_classes(graph, summ, tm))
        findings.extend(self._check_globals(project, graph, summ, tm))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    # -- self.<attr> races ---------------------------------------------------
    def _check_classes(self, graph, summ, tm):
        by_class = {}
        for fi in graph.functions:
            if fi.self_class is not None:
                by_class.setdefault(fi.self_class, []).append(fi)
        findings = []
        for ci, members in by_class.items():
            src = ci.src
            known_locks, _canon = summ.file_locks(src)
            self_locks = frozenset(l for l in known_locks
                                   if l.startswith("self."))
            annotated = _annotated_attrs(src, ci.node)
            lock_attrs = {l.split(".", 1)[1] for l in self_locks}
            method_names = set(ci.methods)
            entry = entry_locksets(graph, summ, members, self_locks,
                                   member_set=set(members))
            per_attr = {}
            for fi in members:
                if fi.name in _CTOR_NAMES:
                    continue
                facts = summ.facts_of(fi)
                base = entry.get(fi, frozenset())
                roots = tm.effective_roots(fi)
                for attr, line, col, is_store, held in facts.accesses:
                    if attr in annotated or attr in lock_attrs \
                            or attr in method_names:
                        continue
                    per_attr.setdefault(attr, []).append(_Access(
                        fi, line, col, is_store,
                        (held & self_locks) | base, roots))
            proposal = sorted(self_locks)[0] if self_locks \
                else "self._lock"
            for attr, accs in sorted(per_attr.items()):
                f = self._race_finding(
                    src, "attribute 'self.%s' of %s" % (attr,
                                                        ci.qualname),
                    accs, tm, proposal, self_locks)
                if f is not None:
                    findings.append(f)
        return findings

    # -- module-global races -------------------------------------------------
    def _check_globals(self, project, graph, summ, tm):
        findings = []
        for src in project.sources:
            module_globals, threadlocal = summ.file_globals(src)
            if not module_globals:
                continue
            known_locks, _canon = summ.file_locks(src)
            glocks = frozenset(l for l in known_locks
                               if not l.startswith("self."))
            annotated = _annotated_globals(src)
            skip = annotated | threadlocal | set(known_locks)
            members = list(graph.functions_of(src))
            entry = entry_locksets(graph, summ, members, glocks,
                                   member_set=set(members))
            per_name = {}
            for fi in members:
                facts = summ.facts_of(fi)
                base = entry.get(fi, frozenset())
                roots = tm.effective_roots(fi)
                for name, line, col, is_store, held \
                        in facts.global_accesses:
                    if name in skip:
                        continue
                    per_name.setdefault(name, []).append(_Access(
                        fi, line, col, is_store,
                        (held & glocks) | base, roots))
            proposal = sorted(glocks)[0] if glocks else "_lock"
            for name, accs in sorted(per_name.items()):
                f = self._race_finding(
                    src, "module global '%s'" % name, accs, tm,
                    proposal, glocks)
                if f is not None:
                    findings.append(f)
        return findings

    # -- the pair search -----------------------------------------------------
    def _race_finding(self, src, label, accs, tm, proposal, locks):
        writes = sorted((a for a in accs if a.is_store),
                        key=lambda a: (a.line, a.col))
        if not writes:
            return None
        others = sorted(accs, key=lambda a: (a.line, a.col))
        for w in writes:
            for a in others:
                if a.fi is w.fi and a.line == w.line and a.col == w.col:
                    continue
                if len(w.roots | a.roots) < 2:
                    continue            # same single root: sequential
                if w.eff & a.eff:
                    continue            # a common lock serialises them
                return self._render(src, label, w, a, tm, proposal,
                                    locks)
        return None

    def _render(self, src, label, w, a, tm, proposal, locks):
        # pick a concrete distinct root pair, preferring to show a
        # real (non-main) root on the write side
        pairs = [(r1, r2) for r1 in w.roots for r2 in a.roots
                 if r1 != r2]
        rw, ra = sorted(pairs, key=lambda p: (p[0] == MAIN_ROOT,
                                              p[1] == MAIN_ROOT,
                                              str(p[0]), str(p[1])))[0]
        wdesc, wvia = tm.describe(rw, w.fi)
        adesc, avia = tm.describe(ra, a.fi)
        via = {src.display} | wvia | avia
        lock_note = "no lock is held at either access" if not locks \
            else "their locksets do not intersect"
        return Finding(
            self.id, src.display, w.line, w.col,
            "%s is written in '%s' (line %d) running under %s, and %s "
            "in '%s' (%s:%d) running under %s — %s, so this is a "
            "cross-thread data race; guard both accesses with %s and "
            "annotate the assignment '# guarded by: %s' so "
            "lock-discipline enforces it everywhere, or justify a "
            "deliberate lock-free fast path with "
            "'# mxlint: disable=thread-race -- why'"
            % (label, w.fi.name, w.line, wdesc,
               "written" if a.is_store else "read", a.fi.name,
               a.fi.src.display, a.line, adesc, lock_note, proposal,
               proposal),
            anchor=src.anchor_for(w.line), via=sorted(via))
