"""resource-release: typestate pairing for locks, entered scopes,
temp files and threads (mxlife family b).

Four acquisition shapes whose release must survive the exception
paths, checked per function over the call graph's try-region map and
the ``may_raise`` summaries:

* **bare lock acquire** — ``<known lock>.acquire()`` outside a
  ``with``: the matching ``release()`` must sit in a ``finally``
  (anywhere in the function); otherwise any raise between acquire
  and release leaves the lock held forever. The fix is almost always
  ``with lock:``.
* **entered scope** — a LOCAL bound to ``....__enter__()`` (a
  ``telemetry.span`` entered by hand because it crosses threads,
  a context entered conditionally): its ``__exit__`` must either sit
  in a ``finally`` or have no in-scan may-raise call between enter
  and exit. A scope parked on ``self.<attr>`` escapes the frame and
  is the ``future-lifecycle`` hygiene check's business instead.
* **temp file** — a name bound from ``tempfile.mkstemp`` or an
  expression carrying a ``".tmp"`` literal, later ``os.replace``/
  ``os.rename``d (the checkpoint/index_put protocol): an
  ``os.unlink``/``os.remove`` of it must exist in an except handler
  or ``finally`` — a crash between create and rename must not leave
  the artifact behind (on the shared filesystems the heartbeat tier
  targets, leftover ``.tmp`` files are exactly what the scanner has
  to defend against).
* **thread join/daemon** — a LOCAL ``threading.Thread``/``Timer``
  constructed without ``daemon=True`` and ``.start()``ed must reach
  its ``join()`` on every path: a may-raise call between start and a
  non-finally join leaks a non-daemon thread that blocks interpreter
  exit. Threads stored on ``self``/returned escape to an owner with
  its own lifecycle and are exempt.

Deliberate exceptions carry a justified
``# mxlint: disable=resource-release -- why`` on the acquisition.
"""
import ast

from ..core import expr_text, resolve_origin

_THREAD_ORIGINS = {"threading.Thread", "threading.Timer"}


def _in_region(try_map, node, regions=("handler", "final")):
    ctx = try_map.get(id(node), ())
    return any(region in regions for _t, region in ctx)


def _tmp_literal(value):
    """Does this bound expression carry a '.tmp' string literal?"""
    for n in ast.walk(value):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and ".tmp" in n.value:
            return True
    return False


class ResourceReleaseRule:
    id = "resource-release"
    fixture_basenames = ("resource_release_violation.py",
                         "resource_release_ok.py")

    def check_project(self, project):
        graph = project.callgraph()
        summ = project.summaries()
        unlinkers = self._unlink_param_map(graph)
        findings = []
        for fi in graph.functions:
            findings.extend(self._check_function(fi, graph, summ,
                                                 unlinkers))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    def _unlink_param_map(self, graph):
        """{FuncInfo: positions of params it os.unlink/os.remove}s —
        an extracted quiet-unlink helper (heartbeat._unlink_quiet)
        counts as cleanup at its call sites, same as a literal
        unlink."""
        from .. import summaries as _summaries
        out = {}
        for fi in graph.functions:
            amap = graph.imports_of(fi.src)
            params = _summaries.file_facts(fi.src).functions.get(
                (fi.qualname, fi.node.lineno))
            if params is None:
                continue
            params = params.params
            positions = set()
            for n in graph.nodes_of(fi):
                if isinstance(n, ast.Call) and n.args \
                        and isinstance(n.args[0], ast.Name) \
                        and resolve_origin(n.func, amap) \
                        in ("os.unlink", "os.remove") \
                        and n.args[0].id in params:
                    positions.add(params.index(n.args[0].id))
            if positions:
                out[fi] = positions
        return out

    # -- shared scanning -----------------------------------------------------
    def _calls(self, graph, fi):
        return [n for n in graph.nodes_of(fi) if isinstance(n, ast.Call)]

    def _risky_lines(self, graph, summ, fi):
        """Lines of unguarded in-scan may-raise call sites, with the
        callee (for the witness)."""
        facts = summ.facts_of(fi)
        out = []
        for callee, line, col in graph.callees(fi):
            if (line, col) in facts.guarded_calls:
                continue
            if summ.may_raise(callee):
                out.append((line, callee))
        return out

    def _check_function(self, fi, graph, summ, unlinkers):
        src = fi.src
        calls = self._calls(graph, fi)
        try_map = graph.try_map_of(fi)
        findings = []
        findings.extend(self._check_locks(fi, src, calls, try_map,
                                          summ))
        findings.extend(self._check_scopes(fi, src, graph, summ, calls,
                                           try_map))
        findings.extend(self._check_tmp_files(fi, src, graph, calls,
                                              try_map, unlinkers))
        findings.extend(self._check_threads(fi, src, graph, summ,
                                            calls, try_map))
        return findings

    # -- (a) bare lock acquire ----------------------------------------------
    def _check_locks(self, fi, src, calls, try_map, summ):
        known, canonical = summ.file_locks(src)
        if not known:
            return []
        acquires, releases = [], []
        for c in calls:
            f = c.func
            if not isinstance(f, ast.Attribute):
                continue
            recv = expr_text(f.value)
            recv = canonical.get(recv, recv)
            if recv not in known:
                continue
            if f.attr == "acquire":
                acquires.append((c, recv))
            elif f.attr == "release":
                releases.append((c, recv))
        out = []
        for c, recv in acquires:
            ok = any(r == recv and _in_region(try_map, rc, ("final",))
                     for rc, r in releases)
            if not ok:
                out.append(src.finding(
                    self.id, c,
                    "'%s' acquires %s outside a 'with' and no "
                    "finally-guarded %s.release() exists — any raise "
                    "between acquire and release leaves the lock held "
                    "forever (every later taker deadlocks); use "
                    "'with %s:' (or release in a finally)"
                    % (fi.name, recv, recv, recv)))
        return out

    # -- (b) entered scopes --------------------------------------------------
    def _check_scopes(self, fi, src, graph, summ, calls, try_map):
        enters = {}                     # var -> enter Call node
        exits = {}                      # var -> [exit Call nodes]
        escapes = set()
        for n in graph.nodes_of(fi):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, ast.Call) \
                    and isinstance(n.value.func, ast.Attribute) \
                    and n.value.func.attr == "__enter__":
                enters.setdefault(n.targets[0].id, n.value)
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "__exit__" \
                    and isinstance(n.func.value, ast.Name):
                exits.setdefault(n.func.value.id, []).append(n)
        if not enters:
            return []
        # escapes: the name stored beyond the frame or passed onward
        for n in graph.nodes_of(fi):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)) \
                            and isinstance(n.value, ast.Name):
                        escapes.add(n.value.id)
            elif isinstance(n, ast.Return) \
                    and isinstance(n.value, ast.Name):
                escapes.add(n.value.id)
            elif isinstance(n, ast.Call):
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if isinstance(a, ast.Name):
                        escapes.add(a.id)
        risky = self._risky_lines(graph, summ, fi)
        out = []
        for var, enter in sorted(enters.items()):
            var_exits = exits.get(var, [])
            if not var_exits:
                if var in escapes:
                    continue
                out.append(src.finding(
                    self.id, enter,
                    "'%s' enters a scope into '%s' via __enter__ and "
                    "never exits it on any path — the span/context "
                    "stays open forever; pair it with a "
                    "finally-guarded %s.__exit__ (or use 'with')"
                    % (fi.name, var, var)))
                continue
            if any(_in_region(try_map, x, ("final",))
                   for x in var_exits):
                continue
            first_exit = min(x.lineno for x in var_exits)
            hit = next((rc for rc in risky
                        if enter.lineno < rc[0] < first_exit), None)
            if hit is not None:
                out.append(src.finding(
                    self.id, enter,
                    "'%s' enters a scope into '%s' at line %d but "
                    "'%s' (line %d) can raise before the __exit__ at "
                    "line %d and no finally guards it — the scope "
                    "leaks on the exception path; move the exit into "
                    "a finally (or use 'with')"
                    % (fi.name, var, enter.lineno, hit[1].name, hit[0],
                       first_exit)))
        return out

    # -- (c) temp files ------------------------------------------------------
    def _check_tmp_files(self, fi, src, graph, calls, try_map,
                         unlinkers):
        amap = graph.imports_of(src)
        tmp_vars = {}                   # var -> binding node
        for n in graph.nodes_of(fi):
            if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                continue
            t, v = n.targets[0], n.value
            if isinstance(t, ast.Tuple) and len(t.elts) == 2 \
                    and isinstance(t.elts[1], ast.Name) \
                    and isinstance(v, ast.Call) \
                    and resolve_origin(v.func, amap) \
                    == "tempfile.mkstemp":
                tmp_vars.setdefault(t.elts[1].id, n)
            elif isinstance(t, ast.Name) and not isinstance(v, ast.Call) \
                    and _tmp_literal(v):
                tmp_vars.setdefault(t.id, n)
        if not tmp_vars:
            return []
        edges = {(line, col): callee for callee, line, col
                 in graph.callees(fi)}
        renamed, cleaned = set(), set()
        for c in calls:
            origin = resolve_origin(c.func, amap)
            first = c.args[0] if c.args else None
            if not isinstance(first, ast.Name):
                continue
            if origin in ("os.replace", "os.rename"):
                renamed.add(first.id)
                continue
            if not _in_region(try_map, c, ("handler", "final")):
                continue
            if origin in ("os.unlink", "os.remove"):
                cleaned.add(first.id)
                continue
            # an in-scan cleanup HELPER counts too: the call sits in a
            # handler/finally and the callee unlinks the position the
            # tmp name rides in (heartbeat._unlink_quiet)
            callee = edges.get((c.lineno, c.col_offset))
            if callee is not None and 0 in unlinkers.get(callee, ()):
                cleaned.add(first.id)
        out = []
        for var, node in sorted(tmp_vars.items()):
            if var not in renamed or var in cleaned:
                continue
            out.append(src.finding(
                self.id, node,
                "'%s' creates temp file '%s' and renames it into "
                "place, but no except/finally unlinks it on failure — "
                "a raise between create and rename leaves the .tmp "
                "artifact behind (the atomic-write protocol "
                "checkpoint.atomic_write follows: write tmp, fsync, "
                "replace, unlink-on-failure); add 'os.unlink(%s)' to "
                "the failure path" % (fi.name, var, var)))
        return out

    # -- (d) threads ---------------------------------------------------------
    def _check_threads(self, fi, src, graph, summ, calls, try_map):
        amap = graph.imports_of(src)
        threads = {}                    # var -> (ctor node, daemon)
        for n in graph.nodes_of(fi):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)):
                continue
            if resolve_origin(n.value.func, amap) not in _THREAD_ORIGINS:
                continue
            daemon = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in n.value.keywords)
            threads[n.targets[0].id] = (n, daemon)
        if not threads:
            return []
        escapes, started, joined, daemonized = set(), {}, {}, set()
        for n in graph.nodes_of(fi):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)) \
                            and isinstance(n.value, ast.Name):
                        escapes.add(n.value.id)
                    if isinstance(t, ast.Attribute) \
                            and t.attr == "daemon" \
                            and isinstance(t.value, ast.Name):
                        daemonized.add(t.value.id)
            elif isinstance(n, ast.Return) \
                    and isinstance(n.value, ast.Name):
                escapes.add(n.value.id)
            elif isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name):
                    if f.attr == "start":
                        started.setdefault(f.value.id, n)
                    elif f.attr == "join":
                        joined.setdefault(f.value.id, n)
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if isinstance(a, ast.Name):
                        escapes.add(a.id)
        risky = self._risky_lines(graph, summ, fi)
        out = []
        for var, (node, daemon) in sorted(threads.items()):
            if daemon or var in daemonized or var not in started:
                continue
            start = started[var]
            join = joined.get(var)
            if join is None:
                if var in escapes:
                    continue
                out.append(src.finding(
                    self.id, start,
                    "'%s' starts non-daemon thread '%s' and neither "
                    "joins it nor marks it daemon — a raise after "
                    "start() leaks a thread that blocks interpreter "
                    "exit; join it in a finally, pass daemon=True, or "
                    "hand it to an owner" % (fi.name, var)))
                continue
            if _in_region(try_map, join, ("final",)):
                continue
            hit = next((rc for rc in risky
                        if start.lineno < rc[0] < join.lineno), None)
            if hit is not None:
                out.append(src.finding(
                    self.id, start,
                    "'%s' starts non-daemon thread '%s' at line %d, "
                    "but '%s' (line %d) can raise before the join at "
                    "line %d and no finally guards it — the "
                    "exception path leaks the thread; join in a "
                    "finally or pass daemon=True"
                    % (fi.name, var, start.lineno, hit[1].name, hit[0],
                       join.lineno)))
        return out
