"""dispatch-hook: one dispatch-reporting entry point.

A raw ``dispatch_hook(...)`` CALL outside ``mxnet_tpu/executor.py``
silently clobbers every other subscriber of the legacy single-slot
hook. Dispatches report via ``executor.record_dispatch`` (which fans
out to the multi-subscriber ``telemetry.on_dispatch`` registry AND the
legacy shim); installing a hook (``executor.dispatch_hook = cb``) is an
assignment, not a call, and stays legal for back-compat monkeypatching.

Replaces the ``grep "dispatch_hook("`` stanza in run_checks.sh — the
AST form additionally stops matching docstrings/comments that merely
mention the name.
"""
import ast

_EXECUTOR_FILE = "mxnet_tpu/executor.py"


class DispatchHookRule:
    id = "dispatch-hook"
    fixture_basenames = ("dispatch_hook_violation.py", "dispatch_hook_ok.py")

    def check_source(self, src, project):
        if src.display.endswith(_EXECUTOR_FILE) \
                or src.display == "executor.py":
            return []
        findings = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name == "dispatch_hook":
                findings.append(src.finding(
                    self.id, node,
                    "raw dispatch_hook(...) call outside %s — report "
                    "dispatches via executor.record_dispatch / subscribe "
                    "via telemetry.on_dispatch" % _EXECUTOR_FILE))
        return findings
