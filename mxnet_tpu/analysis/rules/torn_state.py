"""torn-state-on-raise: a mutation whose restore only runs on the
fall-through path (mxlife family c).

The bug class behind several past review fixes (queue-depth
accounting, breaker counters): a ``self.<attr>`` (or a subscript
through one) is written, an in-scan callee that
:meth:`~..summaries.Summaries.may_raise` runs, and the
restoring/second write to the SAME target sits later in the same
suite with NO enclosing try — an exception between the two writes
tears the state (the counter stays bumped, the flag stays set) and
every later reader sees the torn value.

Shape matched, deliberately narrow (conservative-quiet):

* first write and restoring write target the same ``self``-rooted
  expression text, at the SAME suite level (``self._depth += 1``
  ... ``self._depth -= 1`` is the canonical instance);
* the risky call between them is an UNGUARDED in-scan may-raise
  site with no enclosing try at all — any try (a handler might
  restore, a finally might) silences the finding rather than
  reasoning about what the handler does;
* constructors are exempt (construction happens-before
  publication), as are targets whose two writes straddle suite
  levels (the restore-on-one-branch shape is legitimate
  state-machine code too often to report on).

The finding anchors at the FIRST write and carries the raise
witness chain. Fix with try/finally (restore in the finally), or
justify a deliberate tear with
``# mxlint: disable=torn-state-on-raise -- why``.
"""
import ast

from ..core import expr_text

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_CTOR_NAMES = ("__init__", "__new__", "__setstate__")


def _self_target_text(node):
    """Canonical text of a self-rooted store target (attribute or
    subscript-through-attribute), or None."""
    base = node
    while isinstance(base, (ast.Attribute, ast.Subscript)):
        base = base.value
    if not (isinstance(base, ast.Name) and base.id == "self"):
        return None
    return expr_text(node)


class TornStateRule:
    id = "torn-state-on-raise"
    fixture_basenames = ("torn_state_violation.py",
                         "torn_state_ok.py")

    def check_project(self, project):
        graph = project.callgraph()
        summ = project.summaries()
        findings = []
        for fi in graph.functions:
            if fi.name in _CTOR_NAMES:
                continue
            findings.extend(self._check_function(fi, graph, summ))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    def _check_function(self, fi, graph, summ):
        # unguarded may-raise call sites with NO enclosing try at all
        facts = summ.facts_of(fi)
        try_map = graph.try_map_of(fi)
        edges = [(callee, line, col) for callee, line, col
                 in graph.callees(fi)
                 if (line, col) not in facts.guarded_calls
                 and summ.may_raise(callee)]
        if not edges:
            return []
        call_index = {(n.lineno, n.col_offset): n
                      for n in graph.nodes_of(fi)
                      if isinstance(n, ast.Call)}
        risky = []
        for callee, line, col in edges:
            node = call_index.get((line, col))
            if node is None or try_map.get(id(node), ()):
                continue
            risky.append((line, callee))
        if not risky:
            return []
        findings = []
        for suite in self._suites(fi.node):
            findings.extend(self._check_suite(fi, suite, risky, summ))
        return findings

    def _suites(self, func_node):
        """Every statement list at any nesting level of the function's
        own scope (nested defs excluded — their bodies are their own
        functions)."""
        out = [func_node.body]
        stack = list(func_node.body)
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
                continue
            for field in ("body", "orelse", "finalbody"):
                suite = getattr(n, field, None)
                if isinstance(suite, list) and suite \
                        and isinstance(suite[0], ast.stmt):
                    out.append(suite)
                    stack.extend(suite)
            for h in getattr(n, "handlers", ()):
                out.append(h.body)
                stack.extend(h.body)
        return out

    def _stores_in(self, stmt):
        """(target text, value-is-a-constant) per self-rooted store of
        a DIRECT statement (not descending into nested suites)."""
        out = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AugAssign):
            targets, value = [stmt.target], None    # a mutation, never
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            return out
        is_const = isinstance(value, ast.Constant)
        for t in targets:
            text = _self_target_text(t)
            if text is not None:
                out.append((text, is_const))
        return out

    def _check_suite(self, fi, suite, risky, summ):
        # per target: first-write index -> later risky stmt -> restore
        writes = []                     # (idx, line, target, is_const)
        for idx, stmt in enumerate(suite):
            for text, is_const in self._stores_in(stmt):
                writes.append((idx, stmt.lineno, text, is_const))
        if len(writes) < 2:
            return []
        findings = []
        reported = set()
        for i, (wi, wline, wtext, wconst) in enumerate(writes):
            if wtext in reported:
                continue
            restore = next(
                ((ri, rline, rconst) for ri, rline, rtext, rconst
                 in writes[i + 1:] if rtext == wtext and ri > wi), None)
            if restore is None:
                continue
            if wconst and not restore[2]:
                # initialize-to-constant then publish-a-computed-value:
                # the exception leaves the value the function CHOSE as
                # its reset state (the kvstore wire-byte idiom), not a
                # torn one. set-flag/restore-flag (const/const) and
                # bump/unbump (aug/aug) pairs still report.
                continue
            lo = suite[wi].lineno
            hi = suite[restore[0]].lineno
            hit = next(((line, callee) for line, callee in risky
                        if lo < line < hi), None)
            if hit is None:
                continue
            reported.add(wtext)
            line, callee = hit
            chain = summ.raise_chain(callee)
            why = "'%s'" % callee.name
            via = {fi.src.display, callee.src.display}
            if chain is not None:
                hops, rline, exc = chain
                prev = callee
                for hop, hline in hops:
                    why += " -> %s (called at %s:%d)" % (
                        hop.name, prev.src.display, hline)
                    via.add(hop.src.display)
                    prev = hop
                why += ", which raises %s at %s:%d" % (
                    exc or "an exception", prev.src.display, rline)
            findings.append(fi.src.finding(
                self.id, suite[wi],
                "'%s' mutates %s here, then calls %s (line %d) with "
                "no enclosing try, and only restores %s on the "
                "fall-through path (line %d) — an exception between "
                "the two writes tears the state for every later "
                "reader; wrap the call in try/finally and restore in "
                "the finally, or justify with "
                "'# mxlint: disable=torn-state-on-raise -- why'"
                % (fi.name, wtext, why, line, wtext, restore[1]),
                via=sorted(via)))
        return findings
