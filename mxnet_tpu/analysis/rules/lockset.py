"""lockset: infer missing ``# guarded by:`` annotations (RacerD-style).

lock-discipline enforces the annotations that EXIST. This rule finds
the shared state nobody remembered to annotate: a ``self.<attr>``
that is accessed under a known lock on some paths and lock-free on
others, in a class that owns a ``threading.Lock``/``RLock``/
``Condition``. The inconsistency itself is the signal — either the
lock-free access is a race, or the locked accesses are cargo cult;
both deserve a human look, and the finding proposes the exact
``# guarded by: <lock>`` annotation to add (after which
lock-discipline enforces it everywhere, forever).

Locksets are computed interprocedurally over the mxflow call graph:
an access's effective lockset is the locks held LEXICALLY at it plus
the function's ENTRY lockset — the intersection, over every resolved
call site, of the locks held by the caller there. A private helper
(``_drain``) called only from inside ``with self._lock:`` blocks
therefore counts as locked without any annotation; one lock-free call
site drops it to the meet (empty), exactly RacerD's treatment. Public
methods and functions with unresolved callers start at the empty
lockset (anyone may call them bare).

Noise control, in the conservative-but-quiet direction:

* only attributes with at least one WRITE among the considered
  accesses are flagged (read-only config set in ``__init__`` is not a
  race);
* ``__init__`` bodies and ``*_locked``-suffix functions are exempt
  (construction happens-before publication; the suffix is the
  documented caller-holds-the-lock convention);
* attributes already annotated ``# guarded by:`` anywhere in the
  class belong to lock-discipline and are skipped here, as are the
  lock/condition objects themselves.
"""
import ast

from ..core import Finding
from ..threads import entry_locksets


def _annotated_attrs(src, class_node):
    """Attr names with a '# guarded by:' annotation anywhere in the
    class body (lock-discipline owns those)."""
    out = set()
    for node in ast.walk(class_node):
        if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
            continue
        if node.lineno not in src.guards:
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out.add(t.attr)
    return out


def _exempt(fi):
    return fi.name == "__init__" or fi.name.endswith("_locked")


class LocksetRule:
    id = "lockset"
    fixture_basenames = ("lockset_violation.py", "lockset_ok.py")

    def check_project(self, project):
        graph = project.callgraph()
        summ = project.summaries()

        by_class = {}
        for fi in graph.functions:
            if fi.self_class is not None:
                by_class.setdefault(fi.self_class, []).append(fi)

        findings = []
        for ci, members in by_class.items():
            src = ci.src
            known_locks, _canonical = summ.file_locks(src)
            self_locks = frozenset(l for l in known_locks
                                   if l.startswith("self."))
            if not self_locks:
                continue
            findings.extend(self._check_class(
                src, ci, members, graph, summ, self_locks))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    def _check_class(self, src, ci, members, graph, summ, self_locks):
        annotated = _annotated_attrs(src, ci.node)
        lock_attrs = {l.split(".", 1)[1] for l in self_locks}
        # self.<method>() references are calls, not state accesses
        method_names = set(ci.methods)
        # entry locksets via the SHARED RacerD-style meet (threads.py):
        # a method that escapes as a value (ref edge) or is callable
        # from outside the class starts at the empty lockset
        entry = entry_locksets(graph, summ, members, self_locks,
                               member_set=set(members))

        # attr -> [(fi, line, col, is_store, effective lockset)]
        per_attr = {}
        for fi in members:
            facts = summ.facts_of(fi)
            base = entry.get(fi, frozenset())
            for attr, line, col, is_store, held in facts.accesses:
                if attr in annotated or attr in lock_attrs \
                        or attr in method_names:
                    continue
                eff = (held & self_locks) | base
                per_attr.setdefault(attr, []).append(
                    (fi, line, col, is_store, eff))

        findings = []
        for attr, accs in sorted(per_attr.items()):
            considered = [a for a in accs if not _exempt(a[0])]
            locked = [a for a in considered if a[4]]
            bare = [a for a in considered if not a[4]]
            if not locked or not bare:
                continue
            if not any(a[3] for a in considered):
                continue                    # no write anywhere: not a race
            # propose the most common lock over the locked accesses
            votes = {}
            for _fi, _l, _c, _s, eff in locked:
                for lock in eff:
                    votes[lock] = votes.get(lock, 0) + 1
            lock = max(sorted(votes), key=lambda k: votes[k])
            ex_fi, ex_line = locked[0][0], locked[0][1]
            first = min(bare, key=lambda a: (a[1], a[2]))
            fi, line, col, is_store, _eff = first
            findings.append(Finding(
                self.id, src.display, line, col,
                "attribute 'self.%s' of %s is accessed under %s in %d "
                "place(s) (e.g. '%s' at line %d) but lock-free here in "
                "'%s' (%s) — if it is shared state, annotate its "
                "assignment '# guarded by: %s' so lock-discipline "
                "enforces it everywhere; if the lock-free access is a "
                "deliberate fast path, add a justified "
                "'# mxlint: disable=lockset -- why'"
                % (attr, ci.qualname, lock, len(locked), ex_fi.name,
                   ex_line, fi.name,
                   "written" if is_store else "read", lock),
                anchor=src.anchor_for(line)))
        return findings
