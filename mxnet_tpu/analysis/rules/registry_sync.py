"""registry-consistency: string registries stay in sync across modules.

Three registries coordinate five-plus modules through bare string
literals, where a typo compiles fine and silently never fires:

* **fault sites** — ``faults.SITES`` declares the names the runtime
  consults (``faults.fire("dispatch")`` in executor.py, ``"d2h"`` in
  serving.py, ...). A ``fire()`` literal not in SITES is an injection
  point that can never inject; a SITES entry no runtime module fires is
  a chaos lane that tests nothing.
* **fused-fallback codes** — ``FusedFallback("<code>", ...)``
  constructions vs the declared ``FUSED_FALLBACK_CODES`` table (bench
  lanes and tests assert on the stable codes).
* **telemetry counters** — ``telemetry.counter_inc("<name>")`` literals
  (and ``"prefix.%s" % x`` / f-string prefixes) vs the declared
  ``telemetry.COUNTERS`` patterns, where a trailing ``.*`` covers
  dynamic tails (codes, sites, causes, kinds).

Both directions are checked: an UNDECLARED use reports at the call
site; an UNUSED declaration reports at the registry. Declarations are
found structurally (a top-level ``SITES`` / ``FUSED_FALLBACK_CODES`` /
``COUNTERS`` literal in any scanned file), so the fixture corpus can
carry miniature registries. Unused-entry checks only run when the scan
actually saw at least one use of that registry kind — linting a single
file must not report the whole world unused.
"""
import ast


def _str_tuple(node):
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return [e.value for e in node.elts]
    return None


def _str_dict_keys(node):
    if isinstance(node, ast.Dict) and node.keys and all(
            isinstance(k, ast.Constant) and isinstance(k.value, str)
            for k in node.keys):
        return [k.value for k in node.keys]
    return None


def _call_name(node):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _literal_first_arg(node):
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _format_prefix(node):
    """The static prefix of a dynamic counter name: ``"a.b.%s" % x``
    -> ``"a.b."`` (None when the first arg isn't a %-format or f-string
    over a literal head)."""
    if not node.args:
        return None
    a = node.args[0]
    if isinstance(a, ast.BinOp) and isinstance(a.op, ast.Mod) \
            and isinstance(a.left, ast.Constant) \
            and isinstance(a.left.value, str):
        return a.left.value.split("%", 1)[0]
    if isinstance(a, ast.JoinedStr) and a.values \
            and isinstance(a.values[0], ast.Constant) \
            and isinstance(a.values[0].value, str) \
            and len(a.values) > 1:
        return a.values[0].value
    return None


def _pattern_covers_name(pattern, name):
    if pattern.endswith(".*"):
        return name.startswith(pattern[:-1]) or name == pattern[:-2]
    return name == pattern


def _pattern_covers_prefix(pattern, prefix):
    """A dynamic use with static ``prefix`` is only guaranteed by a
    wildcard whose stem contains the whole prefix."""
    return pattern.endswith(".*") and prefix.startswith(pattern[:-1])


class RegistryConsistencyRule:
    id = "registry-consistency"
    fixture_basenames = ("registry_violation", "registry_ok")

    def check_project(self, project):
        findings = []
        decls = {"SITES": [], "FUSED_FALLBACK_CODES": [], "COUNTERS": []}
        registry_stmt_strings = set()     # id()s of declaration nodes

        for src in project.sources:
            for node in src.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                tname = node.targets[0].id
                if tname in ("SITES", "COUNTERS"):
                    vals = _str_tuple(node.value)
                elif tname == "FUSED_FALLBACK_CODES":
                    vals = _str_dict_keys(node.value)
                else:
                    continue
                if vals is None:
                    continue
                decls[tname].append((src, node, vals))
                for sub in ast.walk(node):
                    registry_stmt_strings.add(id(sub))

        # one declaration per registry kind per scan: silently binding
        # an arbitrary one (e.g. a fixture mini-registry when tests/ and
        # the runtime are scanned together) would judge every real use
        # against the wrong table — duplicates are findings, and the
        # cross-check proceeds against the FIRST in file order
        def pick(tname):
            found = decls[tname]
            if not found:
                return None
            first = found[0]
            for src, node, _vals in found[1:]:
                findings.append(src.finding(
                    self.id, node,
                    "duplicate %s declaration in this scan — %s:%d "
                    "already declares it and uses are cross-checked "
                    "against that one; lint the conflicting path sets "
                    "separately" % (tname, first[0].display,
                                    first[1].lineno)))
            return first

        sites_decl = pick("SITES")
        codes_decl = pick("FUSED_FALLBACK_CODES")
        counters_decl = pick("COUNTERS")

        # -- collect uses ----------------------------------------------------
        fire_uses, code_uses, counter_uses = [], [], []
        counter_prefix_uses = []
        for src in project.sources:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name == "fire":
                    lit = _literal_first_arg(node)
                    if lit is not None:
                        fire_uses.append((src, node, lit))
                elif name == "FusedFallback":
                    lit = _literal_first_arg(node)
                    if lit is not None:
                        code_uses.append((src, node, lit))
                elif name in ("counter_inc", "record_fault_counter"):
                    lit = _literal_first_arg(node)
                    if lit is not None:
                        counter_uses.append((src, node, lit))
                    else:
                        pfx = _format_prefix(node)
                        if pfx:
                            counter_prefix_uses.append((src, node, pfx))

        # -- fault sites -----------------------------------------------------
        if sites_decl is not None:
            src, node, declared = sites_decl
            dset = set(declared)
            used = set()
            for usrc, unode, lit in fire_uses:
                used.add(lit)
                if lit not in dset:
                    findings.append(usrc.finding(
                        self.id, unode,
                        "faults.fire(%r): site not declared in "
                        "faults.SITES (%s) — an undeclared site never "
                        "fires; add it to SITES or fix the typo"
                        % (lit, ", ".join(sorted(dset))),
                        via=(src.display,)))
            if used:
                for missing in [s for s in declared if s not in used]:
                    findings.append(src.finding(
                        self.id, node,
                        "faults.SITES entry %r is never consulted by "
                        "any scanned faults.fire() call — dead chaos "
                        "site; wire it in or drop the declaration"
                        % missing))

        # -- fused-fallback codes -------------------------------------------
        if codes_decl is not None:
            src, node, declared = codes_decl
            dset = set(declared)
            used = set()
            for usrc, unode, lit in code_uses:
                used.add(lit)
                if lit not in dset:
                    findings.append(usrc.finding(
                        self.id, unode,
                        "FusedFallback(%r): code not declared in "
                        "FUSED_FALLBACK_CODES — bench lanes and tests "
                        "key on the declared codes" % lit,
                        via=(src.display,)))
            if used:
                for missing in [c for c in declared if c not in used]:
                    findings.append(src.finding(
                        self.id, node,
                        "FUSED_FALLBACK_CODES entry %r is never "
                        "constructed by any scanned FusedFallback() "
                        "call — dead fallback code" % missing))

        # -- telemetry counters ---------------------------------------------
        if counters_decl is not None:
            src, node, declared = counters_decl
            for usrc, unode, lit in counter_uses:
                if not any(_pattern_covers_name(p, lit)
                           for p in declared):
                    findings.append(usrc.finding(
                        self.id, unode,
                        "counter_inc(%r): counter not declared in "
                        "telemetry.COUNTERS — declare it (a '.*' "
                        "pattern covers dynamic tails) or fix the "
                        "name" % lit, via=(src.display,)))
            for usrc, unode, pfx in counter_prefix_uses:
                if not any(_pattern_covers_prefix(p, pfx)
                           for p in declared):
                    findings.append(usrc.finding(
                        self.id, unode,
                        "counter_inc(%r...): dynamic counter prefix "
                        "not covered by any telemetry.COUNTERS '.*' "
                        "pattern" % pfx, via=(src.display,)))
            if counter_uses or counter_prefix_uses:
                # the registry module's own internal writes (the
                # record_* helpers format names straight into the
                # locked dict) count as uses via their string constants
                internal = set()
                for n in ast.walk(src.tree):
                    if id(n) in registry_stmt_strings:
                        continue
                    if isinstance(n, ast.Constant) \
                            and isinstance(n.value, str):
                        internal.add(n.value)
                        if "%" in n.value:
                            internal.add(n.value.split("%", 1)[0])
                for p in declared:
                    lits = [l for _s, _n, l in counter_uses]
                    pfxs = [x for _s, _n, x in counter_prefix_uses]
                    hit = (any(_pattern_covers_name(p, l) for l in lits)
                           or any(_pattern_covers_prefix(p, x)
                                  for x in pfxs)
                           or any(_pattern_covers_name(p, l)
                                  or _pattern_covers_prefix(p, l)
                                  for l in internal))
                    if not hit:
                        findings.append(src.finding(
                            self.id, node,
                            "telemetry.COUNTERS pattern %r matches no "
                            "scanned counter_inc() call — dead "
                            "declaration" % p))
        return findings
