"""jit-site: every program compiles through the instrumented wrapper.

Any ``jax.jit`` / ``jax.pmap`` / ``pjit`` CALL or DECORATOR — resolved
through import aliases, so ``from jax import jit as J`` and
``import jax.experimental.pjit as P`` are seen — is a finding unless it
is the ONE site inside ``executor._InstrumentedProgram`` carrying the
``"the ONE instrumented jit site"`` marker comment (which must live in
``mxnet_tpu/executor.py`` — a marker anywhere else is itself a
finding). A raw jit dodges every program-card guarantee: explicit
``lower().compile()`` introspection, recompile-cause diagnosis, OOM
enrichment, the persisted compile-cache tier and, on the serving path,
the one-compile-per-bucket accounting.

This replaces the ``grep "jax\\.jit("`` stanza in run_checks.sh, which
an aliased import walked straight past and which could not see
decorator form at all. Grandfathered pre-wrapper sites (component
kernels in metric/optimizer/kvstore/gluon/ops/rtc/parallel) live in
``tools/mxlint_baseline.json``.
"""
import ast

# dotted origins that compile a program. jax.experimental.pjit.pjit is
# the legacy spelling; jax.pjit the re-export.
_TARGETS = {
    "jax.jit": "jax.jit",
    "jax.pmap": "jax.pmap",
    "jax.pjit": "pjit",
    "jax.experimental.pjit.pjit": "pjit",
}

_EXECUTOR_FILE = "mxnet_tpu/executor.py"


def resolve_jit_target(src, node, aliases):
    """The _TARGETS label for a Name/Attribute expr, or None."""
    origin = src.resolve(node, aliases)
    return _TARGETS.get(origin) if origin else None


def partial_jit_target(src, call, aliases):
    """The jit label wrapped by a ``functools.partial(jax.jit, ...)``
    call, or None. The ``@functools.partial(jax.jit, static_argnums=…)``
    decorator idiom builds a program factory just like a direct call —
    flagging the partial construction covers the decorator, assignment
    and immediate-call forms at once."""
    if not isinstance(call, ast.Call) or not call.args:
        return None
    if src.resolve(call.func, aliases) not in ("functools.partial",
                                               "partial"):
        return None
    return resolve_jit_target(src, call.args[0], aliases)


class JitSiteRule:
    id = "jit-site"
    fixture_basenames = ("jit_site_violation.py", "jit_site_ok.py")

    def check_source(self, src, project):
        findings = []
        aliases = src.import_aliases()
        in_executor = src.display.endswith(_EXECUTOR_FILE) \
            or src.display == "executor.py"
        marked = set(src.jit_marker_lines)

        def flag(node, label, how):
            if node.lineno in marked and in_executor:
                marked.discard(node.lineno)     # each marker covers ONE site
                return
            findings.append(src.finding(
                self.id, node,
                "raw %s %s outside the instrumented wrapper — route "
                "programs through executor._InstrumentedProgram so they "
                "get a program card (telemetry.programs()), recompile "
                "diagnosis, OOM enrichment and the persisted compile "
                "cache" % (label, how)))

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                label = resolve_jit_target(src, node.func, aliases)
                if label:
                    flag(node, label, "call")
                else:
                    label = partial_jit_target(src, node, aliases)
                    if label:
                        flag(node, label, "functools.partial wrap")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    label = resolve_jit_target(src, target, aliases)
                    if label:
                        flag(dec, label, "decorator")

        if not in_executor:
            for line in sorted(src.jit_marker_lines):
                findings.append(src.finding(
                    self.id, line,
                    "'%s' marker outside %s — the instrumented site is "
                    "singular by contract"
                    % ("the ONE instrumented jit site", _EXECUTOR_FILE)))
        return findings
