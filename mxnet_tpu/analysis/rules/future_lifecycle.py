"""future-lifecycle: every owned future resolves exactly once, on
every path (mxlife family a).

The chaos/postmortem lanes gate "zero hung futures" dynamically; this
rule proves it statically, including on the exception paths the lanes
cannot enumerate. Over the :mod:`..lifecycle` typestate model:

* **strand** — a code path that OWNS a request (constructed a
  future-bearing object, dequeued/popped one, or iterates a batch
  parameter) and reaches a function exit — a return, an own
  ``raise``, or the raise-edge of an in-scan callee that
  :meth:`~..summaries.Summaries.may_raise` — with the future neither
  resolved (``set_result``/``set_exception``) nor handed onward
  (transfer to a container/attr/unknown callee, or a pass to an
  in-scan callee that discharges that parameter on every path). The
  finding names the stranding exit and, for a raise-edge, the full
  witness chain down to the origin ``raise``.

* **double-resolve** — one path resolving the same future twice,
  unconditionally (a resolve under an ``if not v.future.done():``
  re-check is the sanctioned idempotent form and never reports).

* **resolution hygiene** — a future class that parks entered scopes
  on itself (``self.wait_span = telemetry.span(...).__enter__()``,
  the serving ``_Request`` shape) must have every TERMINAL resolver
  close at least one of them: when sibling resolvers in the scan
  pair ``v.future.set_*`` with ``v.<span>.__exit__`` and one
  resolver closes none, the requests failing through that path leak
  their entered spans (the flight recorder's "every entered span
  exits" promise, and the latency percentiles, silently exclude
  exactly the interesting requests).

Deliberate fire-and-forget futures carry a justified
``# mxlint: disable=future-lifecycle -- why`` on the owning line.
"""
from ..core import Finding
from ..lifecycle import file_has_lifecycle_surface, resolve_target


def _chain_text(summ, callee, via):
    """' (may raise: chain...)' suffix for a raise-edge exit."""
    chain = summ.raise_chain(callee)
    if chain is None:
        return ""
    hops, line, exc = chain
    text = "'%s'" % callee.name
    prev = callee
    for hop, hline in hops:
        text += " -> %s (called at %s:%d)" % (hop.name,
                                              prev.src.display, hline)
        via.add(hop.src.display)
        prev = hop
    via.add(prev.src.display)
    text += " raises %s at %s:%d" % (exc or "an exception",
                                     prev.src.display, line)
    return text


class FutureLifecycleRule:
    id = "future-lifecycle"
    fixture_basenames = ("future_lifecycle_violation.py",
                         "future_lifecycle_ok.py")

    def check_project(self, project):
        if not any(file_has_lifecycle_surface(s)
                   for s in project.sources):
            return []
        model = project.lifecycle()
        summ = project.summaries()
        findings = []
        for fi, res in sorted(model.results.items(),
                              key=lambda kv: (kv[0].src.display,
                                              kv[0].line)):
            if res.gave_up:
                continue
            src = fi.src
            seen = set()
            for var, own_line, exit_line, why in res.strands:
                # interest filter: the object must touch the future
                # machinery — for a loop element, WITHIN that loop
                # (a reused variable name in another loop of the same
                # function earns nothing)
                if why[0] == "loop":
                    lo, hi = why[1], why[2]
                    if not any(v == var and lo <= l <= hi
                               for v, l in res.interest):
                        continue
                elif not any(v == var for v, _l in res.interest):
                    continue
                key = (var, exit_line, why[0])
                if key in seen:
                    continue
                seen.add(key)
                via = {src.display}
                if why[0] == "call":
                    how = ("'%s' (called at line %d) can raise — %s — "
                           "and the exception escapes '%s'"
                           % (why[1].name, exit_line,
                              _chain_text(summ, why[1], via), fi.name))
                elif why[0] == "loop":
                    how = ("the loop iteration ending at line %d moves "
                           "to the next element" % exit_line)
                elif why[0] == "raise":
                    how = "'%s' raises %s at line %d" % (
                        fi.name, why[1], exit_line)
                else:
                    how = ("'%s' returns at line %s" % (
                        fi.name, exit_line))
                findings.append(src.finding(
                    self.id, exit_line,
                    "'%s' owns request '%s' (acquired at line %d) but "
                    "this path leaves its future UNRESOLVED: %s. Every "
                    "outgoing path must set_result/set_exception "
                    "exactly once or hand ownership to a resolving "
                    "callee; resolve it in an except/finally, or "
                    "justify a deliberate fire-and-forget with "
                    "'# mxlint: disable=future-lifecycle -- why'"
                    % (fi.name, var, own_line, how),
                    via=sorted(via)))
            for var, line, first_line in res.doubles:
                findings.append(src.finding(
                    self.id, line,
                    "'%s' resolves request '%s' a SECOND time here "
                    "(first resolved at line %d) on one path — the "
                    "second set_result/set_exception raises "
                    "InvalidStateError at runtime; guard the late "
                    "resolve with 'if not %s.future.done():' or "
                    "restructure so each path resolves once"
                    % (fi.name, var, first_line, var)))
        findings.extend(self._check_span_hygiene(project, model))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings

    # -- resolution hygiene --------------------------------------------------
    def _check_span_hygiene(self, project, model):
        spans = model.span_attr_universe()
        if not spans:
            return []
        # pairing evidence: some resolver in the scan closes them
        paired = [fi for fi in model.resolve_sites
                  if model.scope_exits.get(fi, set()) & spans]
        if not paired:
            return []
        example = paired[0]
        findings = []
        for fi, sites in sorted(model.resolve_sites.items(),
                                key=lambda kv: (kv[0].src.display,
                                                kv[0].line)):
            if fi.name == "__init__":
                continue
            if model.scope_exits.get(fi, set()) & spans:
                continue
            # only var-rooted terminal resolvers (v.future.set_*)
            site = next((s for s in sites
                         if resolve_target(s)[1]), None)
            if site is None:
                continue
            var, _viaf = resolve_target(site)
            findings.append(fi.src.finding(
                self.id, site,
                "'%s' terminally resolves '%s.future' without closing "
                "any of the request's entered scopes (%s) — sibling "
                "resolver '%s' (%s:%d) closes them, so requests "
                "failing through THIS path leak their entered spans "
                "(the recorder's every-entered-span-exits promise, "
                "and the latency percentiles, silently exclude them); "
                "call the span __exit__s before resolving"
                % (fi.name, var, ", ".join(sorted(spans)),
                   example.name, example.src.display, example.line),
                via=sorted({fi.src.display, example.src.display})))
        return findings
