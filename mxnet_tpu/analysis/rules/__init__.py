"""The mxlint rule set. One module per rule; ``rule_table()`` maps the
stable rule id to a singleton instance. Adding a rule:

1. new module here with a class exposing ``id`` and ``check_source(src,
   project)`` (per-file) and/or ``check_project(project)`` (cross-file,
   runs once after every file parsed), plus ``fixture_basenames``
   naming its violation/compliant fixture pair under
   ``tests/lint_fixtures/`` (the meta-test and ``--explain`` read it);
2. register it in ``rule_table()`` below and in
   ``core.ALL_RULE_IDS`` (report order);
3. a seeded-violation + compliant-twin fixture pair under
   ``tests/lint_fixtures/`` and assertions in ``tests/test_mxlint.py``.
"""
from ..core import ALL_RULE_IDS

_TABLE = None


def rule_table():
    """{rule id: rule instance}; built lazily, one instance per process
    (rules are stateless between runs)."""
    global _TABLE
    if _TABLE is None:
        from . import (jit_site, dispatch_hook, lock_discipline,
                       lockset, thread_race, host_sync, trace_purity,
                       donation, collective, future_lifecycle,
                       resource_release, torn_state, registry_sync)
        instances = [jit_site.JitSiteRule(),
                     dispatch_hook.DispatchHookRule(),
                     lock_discipline.LockDisciplineRule(),
                     lockset.LocksetRule(),
                     thread_race.ThreadRaceRule(),
                     host_sync.HostSyncRule(),
                     trace_purity.TracePurityRule(),
                     donation.DonationRule(),
                     collective.CollectiveDisciplineRule(),
                     future_lifecycle.FutureLifecycleRule(),
                     resource_release.ResourceReleaseRule(),
                     torn_state.TornStateRule(),
                     registry_sync.RegistryConsistencyRule()]
        _TABLE = {r.id: r for r in instances}
        missing = set(ALL_RULE_IDS) - set(_TABLE)
        assert not missing, "rules not registered: %s" % missing
    return _TABLE
