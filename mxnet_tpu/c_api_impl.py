"""Python side of the general C API (src/c_api.cc).

Parity: the reference's src/c_api/{c_api.cc,c_api_ndarray.cc,
c_api_symbolic.cc,c_api_executor.cc} — the 159-function MXNET_DLL ABI
(include/mxnet/c_api.h). The C library embeds CPython (the same design
as c_predict: one inference/training stack, one ABI) and calls the
helpers here; handles on the C side are owned PyObject* of the framework
objects themselves, so every language binding drives the exact code path
Python users do.

All pointer arguments arrive as integer addresses; ctypes does the raw
memory traffic so the C side stays a thin marshalling layer.
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

# Standalone C programs (no Python host) must not grab the TPU: the
# embedded interpreter is usually a deployment/inference context.
if os.environ.get("MXNET_TPU_FORCE_CPU") == "1":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ndarray.ndarray import NDArray
from mxnet_tpu.ops.registry import get_op, list_ops

# reference dtype codes (include/mxnet/base.h TypeFlag)
_DTYPE_BY_CODE = {0: np.float32, 1: np.float64, 2: np.float16,
                  3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64}
_CODE_BY_DTYPE = {np.dtype(v): k for k, v in _DTYPE_BY_CODE.items()}

_GRAD_REQ_BY_CODE = {0: "null", 1: "write", 2: "write", 3: "add"}


def _ctx(dev_type, dev_id):
    # reference dev_type: 1=cpu, 2=gpu, 3=cpu_pinned; the accelerator is
    # mx.tpu here (gpu maps onto it for source compatibility)
    if dev_type == 1:
        return mx.cpu(dev_id)
    if dev_type == 3:
        return mx.cpu_pinned(dev_id)
    return mx.tpu(dev_id)


# -- NDArray ----------------------------------------------------------------

def ndarray_create(shape, dev_type, dev_id, delay_alloc, dtype_code):
    dt = _DTYPE_BY_CODE[int(dtype_code)]
    return mx.nd.zeros(tuple(int(s) for s in shape),
                       ctx=_ctx(dev_type, dev_id), dtype=dt)


def ndarray_sync_copy_from(nd, ptr, size):
    n = int(size)
    buf = (ctypes.c_char * (n * nd.dtype.itemsize)).from_address(int(ptr))
    arr = np.frombuffer(buf, dtype=nd.dtype, count=n).reshape(nd.shape)
    nd[:] = arr.copy()


def ndarray_sync_copy_to(nd, ptr, size):
    src = np.ascontiguousarray(nd.asnumpy())
    n = int(size)
    if n != src.size:
        raise MXNetError("copy size %d != ndarray size %d" % (n, src.size))
    ctypes.memmove(int(ptr), src.ctypes.data, n * src.dtype.itemsize)


def ndarray_shape(nd):
    return [int(s) for s in nd.shape]


def ndarray_dtype(nd):
    return _CODE_BY_DTYPE[np.dtype(nd.dtype)]


def ndarray_wait(nd):
    nd.wait_to_read()


def wait_all():
    mx.nd.waitall()


def ndarray_save(fname, nds, keys):
    if keys:
        mx.nd.save(fname, dict(zip(keys, nds)))
    else:
        mx.nd.save(fname, list(nds))


def ndarray_load(fname):
    data = mx.nd.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return [data[k] for k in keys], keys
    return list(data), []


# -- operators --------------------------------------------------------------

def op_names():
    return list_ops()


def op_exists(name):
    """Handle-creation validation (the reference's NNGetOpHandle errors on
    unknown names rather than letting arbitrary attributes be invoked)."""
    return name in list_ops()


def imperative_invoke(op_name, inputs, keys, vals, outputs):
    """(parity: MXImperativeInvoke / c_api_ndarray.cc). ``outputs`` is
    either None (op allocates) or a list of existing NDArrays to write
    into — the reference's in-place output contract."""
    if not op_exists(op_name):
        raise MXNetError("operator %r is not registered" % op_name)
    from mxnet_tpu import nd
    fn = getattr(nd, op_name, None)
    params = {k: _parse_val(v) for k, v in zip(keys, vals)}
    if fn is not None:
        res = fn(*inputs, **params)
    else:
        op = get_op(op_name)
        from mxnet_tpu.imperative import invoke
        res = invoke(op, list(inputs), params)
    if not isinstance(res, (list, tuple)):
        res = [res]
    if outputs:
        if len(outputs) != len(res):
            raise MXNetError(
                "%s produces %d outputs but %d output handles were given"
                % (op_name, len(res), len(outputs)))
        for dst, src in zip(outputs, res):
            if src is not dst:  # mutating ops already wrote in place
                src.copyto(dst)
        return list(outputs)
    return list(res)


def _parse_val(v):
    """Parse a C-string param value the way the reference's dmlc parameter
    parser does (kwargs always arrive as strings over the C ABI)."""
    import ast
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


# -- symbols ----------------------------------------------------------------

def symbol_from_json(json_str):
    return mx.sym.load_json(json_str)


def symbol_from_file(path):
    return mx.sym.load(path)


def symbol_arguments(sym):
    return sym.list_arguments()


def symbol_outputs(sym):
    return sym.list_outputs()


def symbol_aux(sym):
    return sym.list_auxiliary_states()


def symbol_infer_shape(sym, names, shapes):
    known = dict(zip(names, [tuple(s) for s in shapes]))
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**known)
    def clean(lst):
        return [list(s) if s is not None else [] for s in lst]
    complete = all(s is not None for s in arg_shapes + out_shapes + aux_shapes)
    return clean(arg_shapes), clean(out_shapes), clean(aux_shapes), complete


# -- executor ---------------------------------------------------------------

def executor_bind(sym, dev_type, dev_id, arg_nds, grad_nds, req_codes,
                  aux_nds):
    reqs = [_GRAD_REQ_BY_CODE[int(c)] for c in req_codes]
    grads = list(grad_nds)  # NULL C handles already arrive as None
    return sym.bind(ctx=_ctx(dev_type, dev_id), args=list(arg_nds),
                    args_grad=grads, grad_req=reqs,
                    aux_states=list(aux_nds) if aux_nds else None)


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def executor_backward(ex, head_grads):
    ex.backward(out_grads=list(head_grads) if head_grads else None)


def executor_outputs(ex):
    return list(ex.outputs)
