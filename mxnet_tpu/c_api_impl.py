"""Python side of the general C API (src/c_api.cc).

Parity: the reference's src/c_api/{c_api.cc,c_api_ndarray.cc,
c_api_symbolic.cc,c_api_executor.cc} — the 159-function MXNET_DLL ABI
(include/mxnet/c_api.h). The C library embeds CPython (the same design
as c_predict: one inference/training stack, one ABI) and calls the
helpers here; handles on the C side are owned PyObject* of the framework
objects themselves, so every language binding drives the exact code path
Python users do.

All pointer arguments arrive as integer addresses; ctypes does the raw
memory traffic so the C side stays a thin marshalling layer.
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

# Standalone C programs (no Python host) must not grab the TPU: the
# embedded interpreter is usually a deployment/inference context.
if os.environ.get("MXNET_TPU_FORCE_CPU") == "1":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ndarray.ndarray import NDArray
from mxnet_tpu.ops.registry import get_op, list_ops

# reference dtype codes (include/mxnet/base.h TypeFlag)
_DTYPE_BY_CODE = {0: np.float32, 1: np.float64, 2: np.float16,
                  3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64}
_CODE_BY_DTYPE = {np.dtype(v): k for k, v in _DTYPE_BY_CODE.items()}

_GRAD_REQ_BY_CODE = {0: "null", 1: "write", 2: "write", 3: "add"}

# module-level python functions that are nnvm ops in the reference
_ND_LEVEL_OPS = frozenset({"cast_storage"})


def _ctx(dev_type, dev_id):
    # reference dev_type: 1=cpu, 2=gpu, 3=cpu_pinned; the accelerator is
    # mx.tpu here (gpu maps onto it for source compatibility)
    if dev_type == 1:
        return mx.cpu(dev_id)
    if dev_type == 3:
        return mx.cpu_pinned(dev_id)
    return mx.tpu(dev_id)


# -- NDArray ----------------------------------------------------------------

def ndarray_create(shape, dev_type, dev_id, delay_alloc, dtype_code):
    dt = _DTYPE_BY_CODE[int(dtype_code)]
    return mx.nd.zeros(tuple(int(s) for s in shape),
                       ctx=_ctx(dev_type, dev_id), dtype=dt)


def ndarray_sync_copy_from(nd, ptr, size):
    n = int(size)
    buf = (ctypes.c_char * (n * nd.dtype.itemsize)).from_address(int(ptr))
    arr = np.frombuffer(buf, dtype=nd.dtype, count=n).reshape(nd.shape)
    nd[:] = arr.copy()


def ndarray_sync_copy_to(nd, ptr, size):
    src = np.ascontiguousarray(nd.asnumpy())
    n = int(size)
    if n != src.size:
        raise MXNetError("copy size %d != ndarray size %d" % (n, src.size))
    ctypes.memmove(int(ptr), src.ctypes.data, n * src.dtype.itemsize)


def ndarray_shape(nd):
    return [int(s) for s in nd.shape]


def ndarray_dtype(nd):
    return _CODE_BY_DTYPE[np.dtype(nd.dtype)]


def ndarray_wait(nd):
    nd.wait_to_read()


def wait_all():
    mx.nd.waitall()


def ndarray_save(fname, nds, keys):
    if keys:
        mx.nd.save(fname, dict(zip(keys, nds)))
    else:
        mx.nd.save(fname, list(nds))


def ndarray_load(fname):
    data = mx.nd.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return [data[k] for k in keys], keys
    return list(data), []


# -- operators --------------------------------------------------------------

def op_names():
    return list_ops()


def op_exists(name):
    """Handle-creation validation (the reference's NNGetOpHandle errors
    on unknown names rather than letting arbitrary attributes be
    invoked). Besides registry ops, a small set of python-implemented
    ops live as mx.nd module functions (cast_storage & friends — nnvm
    ops in the reference); they are invokable too, but dunder/private
    names never are."""
    if name in list_ops():
        return True
    # ops the reference registers in nnvm but we implement as module-
    # level python (sparse storage conversion) — an explicit list, NOT a
    # blanket getattr: handing out handles for arbitrary nd attributes
    # (save, array, NDArray...) would defeat this validation
    return name in _ND_LEVEL_OPS


def imperative_invoke(op_name, inputs, keys, vals, outputs):
    """(parity: MXImperativeInvoke / c_api_ndarray.cc). ``outputs`` is
    either None (op allocates) or a list of existing NDArrays to write
    into — the reference's in-place output contract."""
    if not op_exists(op_name):
        raise MXNetError("operator %r is not registered" % op_name)
    from mxnet_tpu import nd
    fn = getattr(nd, op_name, None)
    params = {k: _parse_val(v) for k, v in zip(keys, vals)}
    if fn is not None:
        res = fn(*inputs, **params)
    else:
        op = get_op(op_name)
        from mxnet_tpu.imperative import invoke
        res = invoke(op, list(inputs), params)
    if not isinstance(res, (list, tuple)):
        res = [res]
    if outputs:
        if len(outputs) != len(res):
            raise MXNetError(
                "%s produces %d outputs but %d output handles were given"
                % (op_name, len(res), len(outputs)))
        for dst, src in zip(outputs, res):
            if src is not dst:  # mutating ops already wrote in place
                src.copyto(dst)
        return list(outputs)
    return list(res)


def _parse_val(v):
    """Parse a C-string param value the way the reference's dmlc parameter
    parser does (kwargs always arrive as strings over the C ABI)."""
    import ast
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


# -- symbols ----------------------------------------------------------------

def symbol_from_json(json_str):
    return mx.sym.load_json(json_str)


def symbol_from_file(path):
    return mx.sym.load(path)


def symbol_arguments(sym):
    return _sym(sym).list_arguments()


def symbol_outputs(sym):
    return _sym(sym).list_outputs()


def symbol_aux(sym):
    return _sym(sym).list_auxiliary_states()


def symbol_infer_shape(sym, names, shapes):
    known = dict(zip(names, [tuple(s) for s in shapes]))
    arg_shapes, out_shapes, aux_shapes = _sym(sym).infer_shape(**known)
    def clean(lst):
        return [list(s) if s is not None else [] for s in lst]
    complete = all(s is not None for s in arg_shapes + out_shapes + aux_shapes)
    return clean(arg_shapes), clean(out_shapes), clean(aux_shapes), complete


# -- executor ---------------------------------------------------------------

def executor_bind(sym, dev_type, dev_id, arg_nds, grad_nds, req_codes,
                  aux_nds):
    reqs = [_GRAD_REQ_BY_CODE[int(c)] for c in req_codes]
    grads = list(grad_nds)  # NULL C handles already arrive as None
    return _sym(sym).bind(ctx=_ctx(dev_type, dev_id), args=list(arg_nds),
                    args_grad=grads, grad_req=reqs,
                    aux_states=list(aux_nds) if aux_nds else None)


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def executor_backward(ex, head_grads):
    ex.backward(out_grads=list(head_grads) if head_grads else None)


def executor_outputs(ex):
    return list(ex.outputs)


# ===========================================================================
# Round-4 tranche: the rest of the high-traffic ABI (parity:
# include/mxnet/c_api.h:359-1269 — runtime, NDArray extras, full MXSymbol
# attr/compose surface, MXExecutorSimpleBind, MXDataIter*, MXKVStore*,
# MXRecordIO*, MXAutograd*, CachedOp).
# ===========================================================================

# reference include/mxnet/ndarray.h:60-63 storage-type codes
_STYPE_CODE = {"default": 0, "row_sparse": 1, "csr": 2}
_DEV_CODE = {"cpu": 1, "gpu": 2, "tpu": 2, "cpu_pinned": 3}


# -- runtime ----------------------------------------------------------------

def version():
    # reference MXNET_VERSION for 0.12.1 (MAJOR*10000 + MINOR*100 + PATCH)
    return 1201


def random_seed(seed):
    mx.random.seed(int(seed))


def notify_shutdown():
    mx.nd.waitall()


def set_num_omp_threads(n):
    os.environ["OMP_NUM_THREADS"] = str(int(n))


def engine_set_bulk_size(size):
    from mxnet_tpu import engine
    return int(engine.set_bulk_size(int(size)))


def profiler_set_config(mode, filename):
    mx.profiler.set_config(profile_all=bool(mode), filename=filename)


def profiler_set_state(state):
    mx.profiler.set_state("run" if int(state) else "stop")


def profiler_dump():
    mx.profiler.dump()


# -- NDArray extras ---------------------------------------------------------

def ndarray_create_none():
    """Placeholder handle later filled by copy/load (reference
    MXNDArrayCreateNone)."""
    return mx.nd.zeros((0,))


def ndarray_slice(nd, begin, end):
    return nd[int(begin):int(end)]  # write-through view, like the reference


def ndarray_at(nd, idx):
    return nd[int(idx)]


def ndarray_reshape(nd, dims):
    return nd.reshape(tuple(int(d) for d in dims))


def ndarray_get_context(nd):
    ctx = nd.context
    return _DEV_CODE.get(ctx.device_type, 1), int(ctx.device_id)


def ndarray_storage_type(nd):
    return _STYPE_CODE.get(getattr(nd, "stype", "default"), 0)


def ndarray_get_grad(nd):
    return nd.grad  # None -> NULL handle on the C side


def ndarray_detach(nd):
    return nd.detach()


def ndarray_set_grad_state(nd, state):
    nd._fresh_grad = bool(state)


def ndarray_get_grad_state(nd):
    return 1 if getattr(nd, "_fresh_grad", False) else 0


def ndarray_sync_copy_from_ndarray(dst, src, i):
    """i < 0 copies the data array; i >= 0 copies src.aux_data(i) — the
    reference contract (c_api.h MXNDArraySyncCopyFromNDArray), where aux
    arrays are csr [indptr, indices] / row_sparse [indices]."""
    i = int(i)
    if i < 0:
        dst._set_data(src._data)
        return
    from .ndarray import sparse as _sp
    if isinstance(src, _sp.CSRNDArray):
        aux = [src._csr_indptr, src._csr_indices]
    elif isinstance(src, _sp.RowSparseNDArray):
        aux = [src._rsp_indices]
    else:
        raise MXNetError(
            "aux_data(%d) requested on dense NDArray (aux arrays exist "
            "only for sparse storage)" % i)
    if i >= len(aux):
        raise MXNetError("aux_data index %d out of range (%d aux arrays)"
                         % (i, len(aux)))
    dst._set_data(aux[i])


def ndarray_save_raw_bytes(nd):
    """Self-describing single-array blob (round-trips through
    ndarray_load_from_raw_bytes; the reference's raw format is its own
    binary layout, mirrored in role, not in bytes). Plain struct-packed
    header + raw data — NO pickle: this is the model-blob entry point
    and must not give untrusted bytes a code-execution surface."""
    import struct
    arr = np.ascontiguousarray(nd.asnumpy())
    dt = str(arr.dtype).encode()
    return (struct.pack("<8sB", b"MXTPRAW2", len(dt)) + dt
            + struct.pack("<B", arr.ndim)
            + struct.pack("<%dq" % arr.ndim, *arr.shape)
            + arr.tobytes())


def ndarray_load_from_raw_bytes(raw):
    import struct
    raw = bytes(raw)
    if raw[:8] != b"MXTPRAW2":
        raise MXNetError("not a raw NDArray blob")
    off = 8
    (dtlen,) = struct.unpack_from("<B", raw, off)
    off += 1
    dt = raw[off:off + dtlen].decode("ascii")
    off += dtlen
    (ndim,) = struct.unpack_from("<B", raw, off)
    off += 1
    shape = struct.unpack_from("<%dq" % ndim, raw, off)
    off += 8 * ndim
    data = np.frombuffer(raw, dtype=np.dtype(dt), offset=off)
    return mx.nd.array(data.reshape(shape).copy())


# -- symbol: atomic creation + compose --------------------------------------

class _AtomicSymbol:
    """An op symbol created but not yet composed with inputs (the
    reference's nnvm node with params only; MXSymbolCompose supplies
    inputs in place)."""

    def __init__(self, op_name, params):
        self.op_name = op_name
        self.params = params
        self.composed = None


def _sym(s):
    """Unwrap a SymbolHandle: composed atomic symbols delegate to their
    composition result."""
    if isinstance(s, _AtomicSymbol):
        if s.composed is None:
            raise MXNetError(
                "atomic symbol %r has not been composed with inputs yet "
                "(call MXSymbolCompose first)" % s.op_name)
        return s.composed
    return s


def symbol_create_atomic(op_name, keys, vals):
    if not op_exists(op_name):
        raise MXNetError("operator %r is not registered" % op_name)
    return _AtomicSymbol(op_name,
                         {k: _parse_val(v) for k, v in zip(keys, vals)})


def symbol_compose(s, name, keys, args):
    """In-place composition (parity: MXSymbolCompose). ``args`` are
    Symbol handles; ``keys`` may be empty for positional composition."""
    args = [_sym(a) for a in args]
    if isinstance(s, _AtomicSymbol):
        import mxnet_tpu.symbol as sym_mod
        fn = getattr(sym_mod, s.op_name, None)
        params = dict(s.params)
        if name:
            params["name"] = name
        if fn is None:
            raise MXNetError("no symbol constructor for %r" % s.op_name)
        if keys:
            s.composed = fn(**dict(zip(keys, args)), **params)
        else:
            s.composed = fn(*args, **params)
        return
    target = _sym(s)
    if keys:
        composed = target(name=name, **dict(zip(keys, args)))
    else:
        composed = target(*args, name=name)
    target._outputs[:] = composed._outputs


def symbol_create_variable(name):
    return mx.sym.Variable(name)


def symbol_create_group(symbols):
    return mx.sym.Group([_sym(s) for s in symbols])


def symbol_save_to_file(s, fname):
    _sym(s).save(fname)


def symbol_to_json(s):
    return _sym(s).tojson()


def symbol_copy(s):
    import copy
    return copy.deepcopy(_sym(s))


def symbol_print(s):
    return _sym(s).debug_str()


def symbol_get_name(s):
    n = _sym(s).name
    return (n if n is not None else "", n is not None)


def symbol_get_attr(s, key):
    v = _sym(s).attr(key)
    return (v if v is not None else "", v is not None)


def symbol_set_attr(s, key, value):
    if isinstance(s, _AtomicSymbol) and s.composed is None:
        s.params["__%s__" % key if not key.startswith("__") else key] = value
        return
    _sym(s)._set_attr(**{key: value})


def symbol_list_attr(s):
    """Flat [key, value, ...] pairs, recursive form uses the
    'nodename$key' convention the reference uses (c_api_symbolic.cc)."""
    out = []
    for node_name, attrs in _sym(s).attr_dict().items():
        for k, v in attrs.items():
            out.append("%s$%s" % (node_name, k))
            out.append(str(v))
    return out


def symbol_list_attr_shallow(s):
    sym = _sym(s)
    out = []
    node = sym._outputs[0][0]
    for k, v in node._extra_attrs.items():
        out.append(k)
        out.append(str(v))
    return out


def symbol_get_internals(s):
    return _sym(s).get_internals()


def symbol_get_children(s):
    return _sym(s).get_children()  # may be None -> NULL handle


def symbol_get_output(s, index):
    return _sym(s)[int(index)]


def symbol_infer_shape_partial(s, names, shapes):
    known = {n: tuple(sh) for n, sh in zip(names, shapes)}
    arg_s, out_s, aux_s = _sym(s).infer_shape_partial(**known)

    def clean(lst):
        return [list(x) if x is not None else [] for x in (lst or [])]
    complete = bool(arg_s) and all(
        x is not None for x in arg_s + out_s + aux_s)
    return clean(arg_s), clean(out_s), clean(aux_s), complete


def symbol_infer_type(s, names, type_codes):
    known = {n: _DTYPE_BY_CODE[int(c)] for n, c in zip(names, type_codes)
             if int(c) != -1}
    arg_t, out_t, aux_t = _sym(s).infer_type(**known)

    def codes(lst):
        return [_CODE_BY_DTYPE[np.dtype(t)] if t is not None else -1
                for t in (lst or [])]
    complete = bool(arg_t) and all(
        t is not None for t in arg_t + out_t + aux_t)
    return codes(arg_t), codes(out_t), codes(aux_t), complete


def op_info(name):
    """(name, description, arg_names, arg_types, arg_descs,
    key_var_num_args) from the registry (parity:
    MXSymbolGetAtomicSymbolInfo reading nnvm op attrs)."""
    op = get_op(name)
    doc = (op.fn.__doc__ or "").strip()
    arg_names = list(op.arg_names)
    arg_types = ["NDArray-or-Symbol"] * len(arg_names)
    extra = sorted(k for k in op.defaults if k not in arg_names)
    for k in extra:
        arg_names.append(k)
        arg_types.append("string, optional")
    key_var = "num_args" if "num_args" in op.defaults else ""
    return (name, doc, arg_names, arg_types, [""] * len(arg_names), key_var)


# -- executor extras --------------------------------------------------------

def executor_simple_bind(s, dev_type, dev_id, g2c_keys, g2c_dev_types,
                         g2c_dev_ids, req_names, req_types, shape_names,
                         shapes, dtype_names, dtype_codes, stype_names,
                         stype_codes):
    """(parity: MXExecutorSimpleBind, c_api_executor.cc:169). Returns
    (executor, in_args, arg_grads, aux_states). Shared-buffer reuse is
    accepted and ignored at the C layer (PJRT owns allocation; reuse is
    an allocator hint in the reference)."""
    sym = _sym(s)
    # reference calling conventions (c_api_executor.cc): names+types is
    # the dict form; names==NULL with ONE type is the global string; a
    # bare list (no names) applies in list_arguments() order
    if req_names:
        grad_req = dict(zip(req_names, req_types))
    elif len(req_types) == 1:
        grad_req = req_types[0]
    elif req_types:
        grad_req = list(req_types)
    else:
        grad_req = "write"
    type_dict = {n: _DTYPE_BY_CODE[int(c)]
                 for n, c in zip(dtype_names, dtype_codes)}
    group2ctx = {k: _ctx(t, i)
                 for k, t, i in zip(g2c_keys, g2c_dev_types, g2c_dev_ids)}
    kwargs = {n: tuple(int(x) for x in sh)
              for n, sh in zip(shape_names, shapes)}
    ex = sym.simple_bind(ctx=_ctx(dev_type, dev_id), grad_req=grad_req,
                         type_dict=type_dict or None,
                         group2ctx=group2ctx or None, **kwargs)
    return (ex, list(ex.arg_arrays),
            [g for g in ex.grad_arrays], list(ex.aux_arrays))


def executor_print(ex):
    outs = ", ".join("%s %s" % (o.shape, o.dtype) for o in ex.outputs)
    return "Executor(outputs=[%s])" % outs


# -- CachedOp ---------------------------------------------------------------

class _CCachedOp:
    """Imperative invocation of a symbol graph with executor reuse
    (parity: reference CachedOp, imperative/cached_op.cc — bind once per
    input signature, then re-run)."""

    def __init__(self, s):
        self.sym = _sym(s)
        self.arg_names = self.sym.list_arguments()
        self.aux_names = self.sym.list_auxiliary_states()
        self._cache = {}

    def __call__(self, inputs):
        n_args = len(self.arg_names)
        if len(inputs) != n_args + len(self.aux_names):
            raise MXNetError(
                "CachedOp expects %d inputs (%d args + %d aux), got %d"
                % (n_args + len(self.aux_names), n_args,
                   len(self.aux_names), len(inputs)))
        args = inputs[:n_args]
        aux = inputs[n_args:]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        ex = self._cache.get(sig)
        if ex is None:
            # bind PRIVATE copies: the executor keeps its bound arrays,
            # and later cache-hit writes must never mutate caller inputs
            ex = self.sym.bind(ctx=args[0].context if args else mx.cpu(),
                               args=[a.copy() for a in args],
                               args_grad=None, grad_req="null",
                               aux_states=[a.copy() for a in aux]
                               if aux else None)
            self._cache[sig] = ex
        else:
            for dst, src in zip(ex.arg_arrays, args):
                dst._set_data(src._data)
            for dst, src in zip(ex.aux_arrays, aux):
                dst._set_data(src._data)
        return list(ex.forward(is_train=False))


def cached_op_create(s):
    return _CCachedOp(s)


def cached_op_invoke(cop, inputs):
    return cop(list(inputs))


# -- autograd ---------------------------------------------------------------

def autograd_set_recording(flag):
    from mxnet_tpu import imperative
    return 1 if imperative.set_recording(bool(flag)) else 0


def autograd_set_training(flag):
    from mxnet_tpu import imperative
    return 1 if imperative.set_training(bool(flag)) else 0


def autograd_is_recording():
    from mxnet_tpu import imperative
    return 1 if imperative.is_recording() else 0


def autograd_is_training():
    from mxnet_tpu import imperative
    return 1 if imperative.is_training() else 0


def autograd_mark_variables(variables, req_codes, grads):
    from mxnet_tpu import autograd
    reqs = [_GRAD_REQ_BY_CODE[int(c)] for c in req_codes]
    autograd.mark_variables(list(variables), list(grads), reqs)


def autograd_backward(outputs, ograds, retain_graph, is_train):
    from mxnet_tpu import autograd
    heads = [o for o in ograds] if ograds else None
    autograd.backward(list(outputs), head_grads=heads,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(is_train))


def autograd_backward_ex(outputs, ograds, variables, retain_graph,
                         create_graph, is_train):
    """(parity: MXAutogradBackwardEx). With ``variables``, returns their
    grads + stype codes instead of writing into attached buffers."""
    from mxnet_tpu import autograd
    heads = list(ograds) if ograds else None
    if not variables:
        autograd.backward(list(outputs), head_grads=heads,
                          retain_graph=bool(retain_graph),
                          train_mode=bool(is_train))
        return [], []
    grads = autograd.grad(list(outputs), list(variables), head_grads=heads,
                          retain_graph=bool(retain_graph),
                          create_graph=bool(create_graph),
                          train_mode=bool(is_train))
    return list(grads), [ndarray_storage_type(g) for g in grads]


def autograd_get_symbol(nd):
    from mxnet_tpu import autograd
    return autograd.get_symbol(nd)


# -- data iterators ---------------------------------------------------------

def _iter_registry():
    return {
        "MNISTIter": mx.io.MNISTIter,
        "CSVIter": mx.io.CSVIter,
        "LibSVMIter": mx.io.LibSVMIter,
        "ImageRecordIter": mx.io.ImageRecordIter,
    }


def list_data_iters():
    return sorted(_iter_registry())


def data_iter_info(name):
    cls = _iter_registry()[name]
    import inspect
    doc = (cls.__doc__ or "").strip()
    try:
        params = [p for p in inspect.signature(cls).parameters
                  if p not in ("args", "kwargs")]
    except (ValueError, TypeError):
        params = []
    return (name, doc, params, ["string"] * len(params),
            [""] * len(params))


class _CDataIter:
    """Iterator handle: owns the python iterator + the current batch."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def next(self):
        try:
            self.batch = self.it.next()
            return 1
        except StopIteration:
            self.batch = None
            return 0


def data_iter_create(name, keys, vals):
    cls = _iter_registry()[name]
    params = {k: _parse_val(v) for k, v in zip(keys, vals)}
    return _CDataIter(cls(**params))


def data_iter_next(h):
    return h.next()


def data_iter_before_first(h):
    h.it.reset()
    h.batch = None


def data_iter_get_data(h):
    if h.batch is None:
        raise MXNetError("no current batch (call MXDataIterNext first)")
    return h.batch.data[0]


def data_iter_get_label(h):
    if h.batch is None:
        raise MXNetError("no current batch (call MXDataIterNext first)")
    return h.batch.label[0]


def data_iter_get_pad(h):
    if h.batch is None:
        raise MXNetError("no current batch (call MXDataIterNext first)")
    return int(h.batch.pad or 0)


def data_iter_get_index(h):
    if h.batch is None:
        raise MXNetError("no current batch (call MXDataIterNext first)")
    idx = getattr(h.batch, "index", None)
    return [int(i) for i in idx] if idx is not None else []


# -- kvstore ----------------------------------------------------------------

def init_ps_env(keys, vals):
    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)


def kvstore_create(type_str):
    return mx.kv.create(type_str or "local")


def kvstore_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=int(priority))


def kvstore_pull(kv, keys, vals, priority):
    kv.pull(list(keys), out=list(vals), priority=int(priority))


def kvstore_pull_row_sparse(kv, keys, vals, row_ids, priority):
    kv.row_sparse_pull(list(keys), out=list(vals), priority=int(priority),
                       row_ids=list(row_ids))


def kvstore_set_gradient_compression(kv, keys, vals):
    kv.set_gradient_compression(
        {k: _parse_val(v) for k, v in zip(keys, vals)})


def kvstore_set_updater(kv, fn_addr, handle_addr, str_fn_addr=0):
    """Install a C callback updater (parity: MXKVStoreSetUpdater/Ex).
    typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
    NDArrayHandle local, void* handle); the Ex form adds
    MXKVStoreStrUpdater(const char* key, ...) for string keys. Handles
    passed to the callback are NEW references (the callback frees them
    with MXNDArrayFree, the reference's ownership contract)."""
    cb = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_void_p, ctypes.c_void_p)(int(fn_addr))         if fn_addr else None
    str_cb = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                              ctypes.c_void_p,
                              ctypes.c_void_p)(int(str_fn_addr))         if str_fn_addr else None

    def updater(key, recv, local):
        handle = handle_addr if handle_addr else None
        is_int_key = isinstance(key, int)
        if is_int_key and cb is None or not is_int_key and str_cb is None:
            raise MXNetError(
                "no C updater registered for %s keys (use "
                "MXKVStoreSetUpdaterEx to install both forms)"
                % ("int" if is_int_key else "string"))
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(recv))
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(local))
        if is_int_key:
            cb(int(key), id(recv), id(local), handle)
        else:
            str_cb(str(key).encode(), id(recv), id(local), handle)

    kv._set_updater(updater)


def kvstore_get_type(kv):
    return kv.type


def kvstore_get_rank(kv):
    return int(kv.rank)


def kvstore_get_group_size(kv):
    return int(kv.num_workers)


def kvstore_barrier(kv):
    kv.barrier()


def kvstore_send_command(kv, cmd_id, cmd_body):
    kv.send_command_to_servers(int(cmd_id), cmd_body)


def kvstore_num_dead_node(kv, node_id, timeout_sec):
    return int(kv.num_dead_node(int(node_id), timeout=int(timeout_sec)))


def kvstore_run_server(kv, controller_addr, handle_addr):
    """SPMD has no server processes (kvstore_server.py role-absorber);
    accept the controller callback for ABI parity and return — the
    reference blocks here running the request loop."""
    return None


# -- recordio ---------------------------------------------------------------

def recordio_writer_create(uri):
    from mxnet_tpu import recordio
    return recordio.MXRecordIO(uri, "w")


def recordio_reader_create(uri):
    from mxnet_tpu import recordio
    return recordio.MXRecordIO(uri, "r")


def recordio_close(h):
    h.close()


def recordio_write_record(h, ptr, size):
    h.write(ctypes.string_at(int(ptr), int(size)))


def recordio_read_record(h):
    return h.read()  # None at EOF -> NULL buf


def recordio_tell(h):
    return int(h.tell())


def recordio_seek(h, pos):
    h.seek(int(pos))


# ===========================================================================
# Final tranche: sparse NDArray ABI, legacy MXFunc*, BindX, monitor
# callback, RTC, shared-mem transport (c_api.h rows not yet covered).
# ===========================================================================

def ndarray_create_sparse(stype_code, shape, dev_type, dev_id, dtype_code,
                          aux_type_codes):
    """(parity: MXNDArrayCreateSparseEx) — aux types are fixed by the
    storage format here (int32/int64 indices), accepted for ABI parity."""
    from mxnet_tpu.ndarray import sparse as _sp
    stypes = {1: "row_sparse", 2: "csr"}
    if int(stype_code) not in stypes:
        raise MXNetError("unknown sparse storage type %d" % stype_code)
    dt = _DTYPE_BY_CODE[int(dtype_code)]
    return _sp.zeros(stypes[int(stype_code)],
                     tuple(int(s) for s in shape),
                     ctx=_ctx(dev_type, dev_id), dtype=dt)


def _aux_arrays(nd):
    from mxnet_tpu.ndarray import sparse as _sp
    if isinstance(nd, _sp.CSRNDArray):
        return [nd._csr_indptr, nd._csr_indices]
    if isinstance(nd, _sp.RowSparseNDArray):
        return [nd._rsp_indices]
    raise MXNetError("dense NDArray has no aux arrays")


def ndarray_get_aux_type(nd, i):
    aux = _aux_arrays(nd)[int(i)]
    return _CODE_BY_DTYPE.get(np.dtype(str(aux.dtype)), 6)  # default int64


def ndarray_get_aux_ndarray(nd, i):
    from mxnet_tpu.ndarray.ndarray import _wrap
    return _wrap(_aux_arrays(nd)[int(i)], nd.context)


def ndarray_get_data_ndarray(nd):
    from mxnet_tpu.ndarray import sparse as _sp
    from mxnet_tpu.ndarray.ndarray import _wrap
    if isinstance(nd, _sp.CSRNDArray):
        return _wrap(nd._csr_data, nd.context)
    if isinstance(nd, _sp.RowSparseNDArray):
        return _wrap(nd._rsp_data, nd.context)
    return _wrap(nd._data, nd.context)


def ndarray_sync_check_format(nd, full_check):
    """(parity: MXNDArraySyncCheckFormat / common/utils.h CheckFormat):
    validate sparse structural invariants, raising on violation."""
    from mxnet_tpu.ndarray import sparse as _sp
    if isinstance(nd, _sp.CSRNDArray):
        indptr = np.asarray(nd._csr_indptr)
        indices = np.asarray(nd._csr_indices)
        if indptr[0] != 0 or indptr[-1] != len(indices):
            raise MXNetError("csr indptr endpoints invalid")
        if (np.diff(indptr) < 0).any():
            raise MXNetError("csr indptr must be non-decreasing")
        if bool(int(full_check)) and indices.size:
            if indices.min() < 0 or indices.max() >= nd.shape[1]:
                raise MXNetError("csr column index out of range")
    elif isinstance(nd, _sp.RowSparseNDArray):
        idx = np.asarray(nd._rsp_indices)
        if (np.diff(idx) <= 0).any() if idx.size > 1 else False:
            raise MXNetError("row_sparse indices must be strictly "
                             "increasing")
        if bool(int(full_check)) and idx.size:
            if idx.min() < 0 or idx.max() >= nd.shape[0]:
                raise MXNetError("row_sparse row index out of range")


def ndarray_get_data_ptr(nd):
    """(parity: MXNDArrayGetData) — a READ-ONLY host view: PJRT owns
    device memory, so the pointer addresses a synced host copy kept
    alive per-thread on the C side (documented divergence; the
    reference hands out the live device pointer)."""
    arr = np.ascontiguousarray(nd.asnumpy())
    return arr  # C side extracts the buffer and keeps it alive


# -- legacy function API (MXListFunctions/MXFuncInvoke) ---------------------
# The reference's "functions" ARE the imperative ops under the legacy
# calling convention (c_api.cc RegisterAPIFunction): scalar params come
# separately from array in/outs.

def func_info(name):
    op = get_op(name)
    doc = (op.fn.__doc__ or "").strip()
    scalars = sorted(k for k in op.defaults if k not in op.arg_names)
    return (name, doc, scalars, ["string"] * len(scalars),
            [""] * len(scalars), "")


def func_describe(name):
    """(num_use_vars, num_scalars, num_mutate_vars, type_mask)."""
    op = get_op(name)
    n_mutate = len(op.mutate) if op.mutate else 0
    n_use = max(int(op.nin) - n_mutate, 0)
    scalars = [k for k in op.defaults if k not in op.arg_names]
    # type_mask: kNDArrayArgBeforeScalar (=1) matches our ordering
    return (n_use, len(scalars), n_mutate, 1)


def func_invoke(name, use_vars, scalars, mutate_vars, extra_keys=None,
                extra_vals=None):
    """(parity: MXFuncInvoke(Ex)) — legacy convention: the op's input
    slots at its registered mutate positions take mutate_vars, the rest
    take use_vars in order; outputs write into mutate_vars. The Ex form
    adds string params (extra_keys/extra_vals) that OVERRIDE the
    positional scalar slots."""
    op = get_op(name)
    mut_positions = set(op.mutate or ())
    scalar_names = sorted(k for k in op.defaults if k not in op.arg_names)
    params = {k: _parse_val(str(v))
              for k, v in zip(scalar_names, scalars)}
    for k, v in zip(extra_keys or (), extra_vals or ()):
        params[k] = _parse_val(v)
    inputs, ui, mi = [], 0, 0
    for pos in range(len(use_vars) + len(mutate_vars)):
        if pos in mut_positions and mi < len(mutate_vars):
            inputs.append(mutate_vars[mi])
            mi += 1
        else:
            inputs.append(use_vars[ui])
            ui += 1
    return imperative_invoke(name, inputs, list(params.keys()),
                             [str(v) for v in params.values()],
                             list(mutate_vars) if mutate_vars else None)


# -- executor extras --------------------------------------------------------

def executor_bind_x(sym, dev_type, dev_id, g2c_keys, g2c_dev_types,
                    g2c_dev_ids, arg_nds, grad_nds, req_codes, aux_nds):
    """(parity: MXExecutorBindX/BindEX — Bind + a group2ctx device map)."""
    reqs = [_GRAD_REQ_BY_CODE[int(c)] for c in req_codes]
    group2ctx = {k: _ctx(t, i)
                 for k, t, i in zip(g2c_keys, g2c_dev_types, g2c_dev_ids)}
    return _sym(sym).bind(ctx=_ctx(dev_type, dev_id), args=list(arg_nds),
                          args_grad=list(grad_nds), grad_req=reqs,
                          aux_states=list(aux_nds) if aux_nds else None,
                          group2ctx=group2ctx or None)


def executor_set_monitor_callback(ex, fn_addr, handle_addr, monitor_all):
    """C monitor callback: void cb(const char* name, NDArrayHandle arr,
    void* handle). The handle passed in is a NEW reference (C side frees
    with MXNDArrayFree, reference ownership contract)."""
    cb = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_void_p)(int(fn_addr))

    def monitor(name, arr):
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(arr))
        cb(str(name).encode(), id(arr),
           handle_addr if handle_addr else None)

    ex.set_monitor_callback(monitor, monitor_all=bool(monitor_all))


# -- RTC (PallasModule-backed; parity: mx.rtc over MXRtc*) ------------------

_RTC_TYPE_NAMES = {0: "float", 1: "double", 2: "half", 3: "uint8_t",
                   4: "int32_t", 5: "int8_t", 6: "int64_t"}


def rtc_module_create(source, options, exports):
    from mxnet_tpu import rtc
    return rtc.PallasModule(source, options=tuple(options),
                            exports=tuple(exports))


def rtc_kernel_create(mod, name, is_ndarray, is_const, dtype_codes):
    # get_kernel's parser wants "(const) type (*) (name)"
    sig = ", ".join(
        ("const %s*" % _RTC_TYPE_NAMES[int(t)] if (nd and c) else
         "%s*" % _RTC_TYPE_NAMES[int(t)] if nd else
         _RTC_TYPE_NAMES[int(t)])
        for nd, c, t in zip(is_ndarray, is_const, dtype_codes))
    return mod.get_kernel(name, sig), [bool(x) for x in is_ndarray], \
        [int(t) for t in dtype_codes]


def rtc_kernel_call(kernel_tuple, dev_id, arg_addrs, gx, gy, gz, bx, by,
                    bz):
    """args arrive as raw addresses: NDArray args are PyObject*,
    scalars are pointers to the value (the reference's void** call
    convention)."""
    kernel, is_ndarray, dtype_codes = kernel_tuple
    ctypes_by_code = {0: ctypes.c_float, 1: ctypes.c_double,
                      2: ctypes.c_uint16, 3: ctypes.c_uint8,
                      4: ctypes.c_int32, 5: ctypes.c_int8,
                      6: ctypes.c_int64}
    args = []
    for addr, nd, code in zip(arg_addrs, is_ndarray, dtype_codes):
        if nd:
            args.append(ctypes.cast(int(addr), ctypes.py_object).value)
        else:
            ct = ctypes_by_code[int(code)]
            args.append(ct.from_address(int(addr)).value)
    kernel.launch(args, _ctx(2, int(dev_id)), (int(gx), int(gy), int(gz)),
                  (int(bx), int(by), int(bz)))


class _LegacyRtc:
    """(parity: the old MXRtcCreate/Push API — fixed input/output lists
    bound at create). The source defines a python function named after
    the kernel taking (*inputs, *outputs) and returning the new output
    arrays; grid/block dims are accepted and ignored (XLA owns
    scheduling). The reference compiled CUDA C here — a direct
    divergence, documented in PARITY.md."""

    def __init__(self, name, input_names, output_names, inputs, outputs,
                 source):
        del input_names, output_names, inputs, outputs  # ABI-shape only:
        # Push supplies the arrays; the create-time lists exist because
        # the reference bound fixed CUDA buffers at create
        from mxnet_tpu import rtc
        self.module = rtc.PallasModule(source, exports=(name,))
        self.fn = self.module._env[name]

    def push(self, inputs, outputs):
        res = self.fn(*[a._data for a in inputs],
                      *[o._data for o in outputs])
        if not isinstance(res, (list, tuple)):
            res = [res]
        for dst, val in zip(outputs, res):
            dst._set_data(val)


def rtc_create(name, input_names, output_names, inputs, outputs, source):
    return _LegacyRtc(name, input_names, output_names, inputs, outputs,
                      source)


def rtc_push(handle, inputs, outputs):
    handle.push(list(inputs), list(outputs))


# -- shared-memory transport ------------------------------------------------

_SHM_COUNTER = [0]


def ndarray_get_shared_mem_handle(nd):
    """(parity: MXNDArrayGetSharedMemHandle) — POSIX shm segment named
    /mxtpu_<pid>_<id>; returns (pid, id). One-shot transport: the
    consumer's ndarray_create_from_shared_mem COPIES and UNLINKS the
    segment (PJRT owns real device memory, so unlike the reference the
    segment cannot back the array's storage — without the unlink every
    push would leak a tmpfs file). Ids come from a process-local
    counter, never object identity (id() values are reused after GC)."""
    arr = np.ascontiguousarray(nd.asnumpy())
    pid = os.getpid()
    _SHM_COUNTER[0] += 1
    seg_id = _SHM_COUNTER[0]
    path = "/dev/shm/mxtpu_%d_%d" % (pid, seg_id)
    with open(path, "wb") as f:
        f.write(arr.tobytes())
    return pid, seg_id


def ndarray_create_from_shared_mem(shared_pid, shared_id, shape,
                                   dtype_code):
    dt = _DTYPE_BY_CODE[int(dtype_code)]
    path = "/dev/shm/mxtpu_%d_%d" % (int(shared_pid), int(shared_id))
    with open(path, "rb") as f:
        raw = f.read()
    os.unlink(path)  # one-shot transport, see get_shared_mem_handle
    arr = np.frombuffer(raw, dtype=dt).reshape(
        tuple(int(s) for s in shape))
    return mx.nd.array(arr.copy())
