"""Multi-process runtime wiring: jax.distributed with a survivable client.

Parity: the reference's multi-host tier is ps-lite — ``KVStore::InitPSEnv``
reads ``DMLC_PS_ROOT_URI``/``DMLC_RANK`` and wires scheduler/server/worker
roles (kvstore.h:254, SURVEY.md §5.3). The TPU-native rebuild has no
roles: every worker is the SAME single program on a process-spanning
mesh, discovered through ``jax.distributed`` exactly as multi-host TPU
pods are driven (the one-program-across-hosts model of the Julia-to-TPU
line, arXiv 1810.09868). ``tools/launch.py`` exports the env this module
reads at import.

Two deviations from a stock ``jax.distributed.initialize``, both in
service of ELASTIC recovery (a dead worker must not take the survivors
down with it):

* the client is built with ``shutdown_on_destruction=False`` and a
  WIDE missed-heartbeat budget: when a peer dies, the coordination
  service's default posture is "ensure all processes shut down if any
  process dies" — precisely wrong for a runtime whose fit loop detects
  the death itself (heartbeat.py liveness), re-meshes over the
  survivors and resumes from the last checkpoint. The coordination
  service keeps its roles (rendezvous, topology exchange); the
  LIVENESS authority is the heartbeat directory.
* shutdown is explicit and conditional: :func:`finalize` runs the
  clean shutdown barrier only when every peer is still live —
  after a member loss (:func:`mark_member_lost`) the survivor skips
  the barrier (it would time out against the dead peer and the
  propagated error would fatally terminate the process mid-exit).

On the CPU backend (the 2-process-on-one-box tier-1 lane) cross-process
collectives need the gloo transport — selected automatically before
backend init.
"""
from __future__ import annotations

import os
import threading

__all__ = ["init_from_env", "initialized", "rank", "process_count",
           "live_ranks", "mark_member_lost", "dead_ranks", "finalize",
           "abort", "ENV_COORDINATOR"]

ENV_COORDINATOR = "MXNET_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "MXNET_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "MXNET_TPU_PROCESS_ID"
# coordination-service heartbeat posture (distinct from the liveness
# heartbeats in heartbeat.py): interval seconds x max missed = how long
# the SERVICE tolerates a silent peer before it propagates a fatal
# error to every task. Elastic recovery needs this window wider than
# the time a survivor takes to detect the death itself and re-mesh.
ENV_HEARTBEAT_S = "MXNET_TPU_DIST_HEARTBEAT_S"
ENV_MAX_MISSED = "MXNET_TPU_DIST_MAX_MISSED"

_lock = threading.Lock()
_state = {"initialized": False,    # guarded by: _lock
          "owns_client": False,    # guarded by: _lock
          "member_lost": False,    # guarded by: _lock
          "dead": frozenset()}     # guarded by: _lock


def _force_cpu_collectives():
    """Select the gloo transport for cross-process CPU collectives when
    the job runs on the host platform (the tier-1 lane; the default CPU
    client has no multi-process collectives at all). A no-op when the
    flag is unknown (older jax) or the platform is an accelerator."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    force_cpu = os.environ.get("MXNET_TPU_FORCE_CPU", "") in ("1", "true")
    if not (force_cpu or "cpu" in plats.split(",")):
        return
    try:
        import jax
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:   # flag unknown on this jax — stock behaviour
        pass


def init_from_env():
    """Wire this process into the job described by the launch env
    (``MXNET_TPU_COORDINATOR``/``_NUM_PROCESSES``/``_PROCESS_ID``, set
    by ``tools/launch.py``). Must run before any backend touch, hence
    from ``mxnet_tpu/__init__``. Returns True when a multi-process
    runtime was (or already is) initialised.

    Connection errors propagate: a worker that cannot reach the
    coordinator must die loudly, not train as a 1-process job.
    """
    addr = os.environ.get(ENV_COORDINATOR)
    if not addr:
        return False
    _force_cpu_collectives()
    import jax
    with _lock:
        if _state["initialized"] or _jax_initialized():
            _state["initialized"] = True
            return True
        nproc = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
        pid = int(os.environ.get(ENV_PROCESS_ID, "0"))
        try:
            _survivable_initialize(addr, nproc, pid)
            _state["owns_client"] = True
        except (ImportError, AttributeError, TypeError):
            # private client surface moved on this jax — fall back to
            # the stock initialize (loses elastic survival, keeps
            # multi-process training). If the SERVICE half already came
            # up before the client constructor rejected a kwarg, tear
            # it down first: the stock initialize refuses to run with
            # a service already set, which would kill the coordinator
            # process (and with it the whole job) at import
            _teardown_partial_service()
            jax.distributed.initialize(coordinator_address=addr,
                                       num_processes=nproc,
                                       process_id=pid)
        _state["initialized"] = True
    return True


def _teardown_partial_service():
    """Undo a half-finished :func:`_survivable_initialize`: shut down
    and clear any coordination service it created so the stock
    ``jax.distributed.initialize`` fallback starts from a clean
    slate."""
    try:
        from jax._src import distributed as _jdist
    except ImportError:
        return
    gs = _jdist.global_state
    service, gs.service = gs.service, None
    if service is not None:
        try:
            service.shutdown()
        except Exception:
            pass


def _survivable_initialize(addr, nproc, pid):
    """``jax.distributed.initialize`` with the elastic posture: a wide
    service/client missed-heartbeat budget and no shutdown-on-destruction
    barrier (see module docstring). Mirrors
    ``jax._src.distributed.State.initialize`` field for field so
    ``jax.distributed.is_initialized()`` and every ``process_index``
    consumer see a normally-initialised runtime."""
    from jax._src import distributed as _jdist
    from jax._src.lib import xla_extension as _xe
    hb_s = int(os.environ.get(ENV_HEARTBEAT_S, "10"))
    max_missed = int(os.environ.get(ENV_MAX_MISSED, "10"))
    gs = _jdist.global_state
    if gs.client is not None:
        raise RuntimeError("distributed client already initialised")
    if pid == 0 and gs.service is None:
        port = addr.rsplit(":", 1)[1]
        gs.service = _xe.get_distributed_runtime_service(
            "[::]:" + port, nproc, heartbeat_interval=hb_s,
            max_missing_heartbeats=max_missed)
    client = _xe.get_distributed_runtime_client(
        addr, pid,
        init_timeout=int(os.environ.get("MXNET_TPU_DIST_INIT_TIMEOUT",
                                        "300")),
        heartbeat_interval=hb_s, max_missing_heartbeats=max_missed,
        shutdown_on_destruction=False, use_compression=True)
    client.connect()
    gs.client = client
    gs.process_id = pid
    gs.num_processes = nproc
    gs.coordinator_address = addr
    if gs.preemption_sync_manager is None:
        gs.initialize_preemption_sync_manager()


def _jax_initialized():
    """Whether the jax distributed client exists (jax's own
    ``is_initialized`` only appeared in later releases)."""
    try:
        from jax._src import distributed as _jdist
        return _jdist.global_state.client is not None
    except Exception:
        return False


def initialized():
    """Whether a multi-process runtime is live."""
    return _jax_initialized()


def rank():
    """This process's index in the job (0 in a single-process run)."""
    if not initialized():
        return int(os.environ.get("DMLC_RANK", 0))
    import jax
    return jax.process_index()


def process_count():
    """Total processes LAUNCHED into the job (dead ones included — use
    :func:`live_ranks` for the surviving membership)."""
    if not initialized():
        return int(os.environ.get("DMLC_NUM_WORKER", 1))
    import jax
    return jax.process_count()


def live_ranks():
    """Sorted surviving process ranks: everything launched minus the
    ranks recorded dead by :func:`mark_member_lost`. The elastic
    re-mesh builds the new dp mesh from exactly this set."""
    with _lock:
        dead = _state["dead"]
    return tuple(r for r in range(process_count()) if r not in dead)


def dead_ranks():
    """Sorted ranks recorded dead so far."""
    with _lock:
        return tuple(sorted(_state["dead"]))


def mark_member_lost(ranks):
    """Record dead peers. From then on :func:`live_ranks` excludes them
    and :func:`finalize` skips the all-tasks shutdown barrier (it can
    never complete against a dead peer, and the propagated barrier
    error would fatally terminate this surviving process)."""
    with _lock:
        _state["member_lost"] = True
        _state["dead"] = _state["dead"] | frozenset(int(r) for r in ranks)


def finalize():
    """Clean multi-process teardown. With every peer live this runs the
    coordination shutdown barrier (all workers should call it at job
    end); after a member loss it only drops the local references, so
    the surviving process exits 0 instead of aborting in the barrier.
    Idempotent; a no-op in single-process runs."""
    with _lock:
        if not _state["initialized"]:
            return
        _state["initialized"] = False
        owns, lost = _state["owns_client"], _state["member_lost"]
    if not owns:
        # stock-initialized runtime: jax.distributed.shutdown owns it
        return
    try:
        from jax._src import distributed as _jdist
    except ImportError:
        return
    gs = _jdist.global_state
    if lost:
        # LEAK the client/service deliberately: destroying them
        # mid-interpreter cancels the coordination channels, the
        # surviving client's error-poll thread observes the
        # cancellation and this jaxlib's default handler FATALLY
        # terminates the process — after the survivor did all the
        # work of recovering. The OS reclaims everything at exit;
        # a survivor that must guarantee a destructor-free exit can
        # call :func:`abort`.
        return
    client, service = gs.client, gs.service
    gs.client = None
    gs.service = None
    gs.preemption_sync_manager = None
    if client is not None:
        client.shutdown()
    if service is not None:
        service.shutdown()


def abort(code=0):
    """Exit the process immediately WITHOUT running destructors — the
    only guaranteed-safe exit on this jaxlib once a peer has died
    abnormally: any teardown of the coordination client/service can
    trip its fatal error-propagation path (a worker dying with a
    Python exception runs C++ destructors whose shutdown-barrier RPC
    drags every surviving peer into a fatal abort ~15 s later; a
    SIGKILL'd or ``abort()``-ed worker does not). Flushes stdio
    first. Dist workers that crash should die THROUGH this; the
    launcher treats any nonzero code as a member death.

    ``os._exit`` skips atexit AND sys.excepthook, so a crashing worker
    aborting here would die with its flight recorder unsaved — exactly
    the rank whose last seconds the fleet postmortem needs (the
    survivor's ``dead_worker`` view gathers peers' dumps from the
    shared flight dir). Bank a ``worker_abort`` postmortem first on
    any nonzero code; best-effort, a recorder failure must not stop
    the exit."""
    import sys
    if int(code) != 0:
        try:
            from . import flight as _flight
            # called from inside an except block (the dist child's
            # crash handler), sys.exc_info() carries the killing
            # exception — the victim's dump should name its killer
            _flight.postmortem("worker_abort", exc=sys.exc_info()[1],
                               extra={"exit_code": int(code)},
                               force=True)
        except Exception:
            pass
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os._exit(int(code))
