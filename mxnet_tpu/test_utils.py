"""Testing fixtures — the numeric-check engine.

Parity: reference ``python/mxnet/test_utils.py`` (SURVEY.md §4): the
key testing ideas are (1) forward-vs-numpy, (2) backward-vs-finite-
difference (``check_numeric_gradient``), (3) cross-backend consistency
(``check_consistency`` — here TPU-vs-CPU instead of GPU-vs-CPU), and
(4) convergence smoke tests.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array as nd_array, zeros as nd_zeros
from . import ndarray as nd

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
           "random_arrays", "check_numeric_gradient", "check_symbolic_forward",
           "check_symbolic_backward", "check_consistency", "simple_forward"]

_default_ctx = [None]


def default_context():
    return _default_ctx[0] or current_context()


def set_default_context(ctx):
    _default_ctx[0] = ctx


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """(parity: test_utils.assert_almost_equal:467)"""
    a, b = _as_np(a), _as_np(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
        idx = np.unravel_index(np.argmax(np.abs(a - b)), a.shape) if a.shape \
            else ()
        raise AssertionError(
            "%s and %s differ: max abs err %g at %s (rtol=%g atol=%g)"
            % (names[0], names[1], float(np.max(np.abs(a - b))), idx, rtol,
               atol))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, stype="default", density=None, dtype=np.float32,
                 ctx=None):
    """(parity: test_utils.rand_ndarray:336 — dense or sparse w/ density)"""
    if stype == "default":
        return nd_array(np.random.uniform(-1, 1, shape).astype(dtype),
                        ctx=ctx)
    density = 0.5 if density is None else density
    dense = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.uniform(0, 1, shape) < density
    dense = dense * mask
    from .ndarray import sparse as sp
    if stype == "row_sparse":
        return sp.cast_storage(nd_array(dense), "row_sparse")
    if stype == "csr":
        return sp.cast_storage(nd_array(dense), "csr")
    raise MXNetError("unknown stype %r" % stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    ex = sym.bind(ctx=ctx, args={k: nd_array(v) for k, v in inputs.items()})
    outs = [o.asnumpy() for o in ex.forward(is_train=is_train)]
    return outs if len(outs) > 1 else outs[0]


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Finite differences vs symbolic backward
    (parity: test_utils.check_numeric_gradient:789). Like the reference,
    the random projection is part of the graph (sum(out * proj) wrapped in
    MakeLoss) so loss-style ops with fixed backward semantics are handled
    uniformly."""
    from . import symbol as _sym
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    location = {k: np.asarray(v, np.float64).astype(np.float32)
                for k, v in location.items()}
    if grad_nodes is None:
        grad_nodes = arg_names

    if len(sym.list_outputs()) > 1:
        raise MXNetError("check_numeric_gradient expects single output")
    proj = _sym.Variable("__random_proj")
    loss = _sym.MakeLoss(_sym.sum(sym * proj))

    # shapes: forward once to get output shape for the projection
    probe = sym.bind(ctx=ctx, args={k: nd_array(v, ctx=ctx)
                                    for k, v in location.items()},
                     aux_states={k: nd_array(v) for k, v in
                                 (aux_states or {}).items()} or None)
    out_shape = probe.forward()[0].shape
    head = np.random.normal(0, 1, out_shape).astype(np.float32)
    location = dict(location)
    location["__random_proj"] = head

    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    grads = {k: nd_zeros(v.shape, ctx=ctx) for k, v in location.items()
             if k in grad_nodes}
    ex = loss.bind(ctx=ctx, args=args, args_grad=grads,
                   aux_states={k: nd_array(v) for k, v in
                               (aux_states or {}).items()} or None)
    ex.forward(is_train=True)
    ex.backward()
    sym_grads = {k: grads[k].asnumpy() for k in grads}

    def f(loc):
        ex2 = loss.bind(ctx=ctx, args={k: nd_array(v, ctx=ctx)
                                       for k, v in loc.items()},
                        aux_states={k: nd_array(v) for k, v in
                                    (aux_states or {}).items()} or None)
        o = ex2.forward(is_train=use_forward_train)[0].asnumpy()
        return float(np.sum(o))

    for name in grad_nodes:
        base = location[name]
        num_grad = np.zeros_like(base)
        flat = base.reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = f(location)
            flat[i] = orig - numeric_eps
            fm = f(location)
            flat[i] = orig
            ng_flat[i] = (fp - fm) / (2 * numeric_eps)
        assert_almost_equal(num_grad, sym_grads[name], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("numeric_%s" % name, "symbolic_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=1e-20,
                           aux_states=None, ctx=None):
    """(parity: test_utils.check_symbolic_forward:921)"""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    ex = sym.bind(ctx=ctx, args=args,
                  aux_states={k: nd_array(v) for k, v in
                              (aux_states or {}).items()} or None)
    outputs = [o.asnumpy() for o in ex.forward()]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=1e-20, aux_states=None, grad_req="write",
                            ctx=None):
    """(parity: test_utils.check_symbolic_backward)"""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    grads = {k: nd_zeros(np.asarray(v).shape, ctx=ctx)
             for k, v in location.items()}
    ex = sym.bind(ctx=ctx, args=args, args_grad=grads, grad_req=grad_req)
    ex.forward(is_train=True)
    ex.backward(out_grads=[nd_array(g, ctx=ctx) for g in out_grads])
    for name, exp in expected.items():
        assert_almost_equal(grads[name].asnumpy(), exp, rtol=rtol, atol=atol,
                            names=("grad_%s" % name, "expected_%s" % name))
    return {k: v.asnumpy() for k, v in grads.items()}


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, rtol=1e-3, atol=1e-4):
    """Run the same graph on several contexts and compare outputs+grads —
    the cross-backend oracle (parity: test_utils.check_consistency; the
    reference compares cpu vs gpu, here cpu vs tpu).

    Default tolerance matches the reference's fp32 cross-backend tol of
    1e-3 (reference python/mxnet/test_utils.py:1267 `tol[np.float32]`).
    TPU transcendental units (tanh/exp are polynomial/exp2 hardware
    approximations) differ from CPU libm by up to a few e-5 absolute for
    O(1) inputs — correct behavior, not a precision bug — so the round-2
    atol of 1e-5 was miscalibrated for a cross-backend oracle."""
    if len(ctx_list) < 2:
        raise MXNetError("need at least two contexts")
    results = []
    np.random.seed(0)
    arg_shapes = None
    for spec in ctx_list:
        ctx = spec["ctx"] if isinstance(spec, dict) else spec
        shapes = {k: v for k, v in spec.items() if k != "ctx"} \
            if isinstance(spec, dict) else {}
        ex = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
        if arg_shapes is None:
            arg_shapes = {k: a.shape for k, a in ex.arg_dict.items()}
            arg_params = arg_params or {
                k: np.random.normal(0, scale, s).astype(np.float32)
                for k, s in arg_shapes.items()}
        for k, v in arg_params.items():
            ex.arg_dict[k][:] = v
        outs = [o.asnumpy() for o in ex.forward(is_train=True)]
        ex.backward()
        grads = {k: g.asnumpy() for k, g in ex.grad_dict.items()
                 if g is not None}
        results.append((outs, grads))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for o, r in zip(outs, ref_outs):
            assert_almost_equal(o, r, rtol=rtol, atol=atol)
        for k in ref_grads:
            assert_almost_equal(grads[k], ref_grads[k], rtol=rtol, atol=atol)
    return results
