"""Testing fixtures — the numeric-check engine.

Parity: reference ``python/mxnet/test_utils.py`` (SURVEY.md §4): the
key testing ideas are (1) forward-vs-numpy, (2) backward-vs-finite-
difference (``check_numeric_gradient``), (3) cross-backend consistency
(``check_consistency`` — here TPU-vs-CPU instead of GPU-vs-CPU), and
(4) convergence smoke tests.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array as nd_array, zeros as nd_zeros
from . import ndarray as nd

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
           "random_arrays", "check_numeric_gradient", "check_symbolic_forward",
           "check_symbolic_backward", "check_consistency", "simple_forward"]

_default_ctx = [None]


def default_context():
    return _default_ctx[0] or current_context()


def set_default_context(ctx):
    _default_ctx[0] = ctx


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """(parity: test_utils.assert_almost_equal:467)"""
    a, b = _as_np(a), _as_np(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
        idx = np.unravel_index(np.argmax(np.abs(a - b)), a.shape) if a.shape \
            else ()
        raise AssertionError(
            "%s and %s differ: max abs err %g at %s (rtol=%g atol=%g)"
            % (names[0], names[1], float(np.max(np.abs(a - b))), idx, rtol,
               atol))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, stype="default", density=None, dtype=np.float32,
                 ctx=None):
    """(parity: test_utils.rand_ndarray:336 — dense or sparse w/ density)"""
    if stype == "default":
        return nd_array(np.random.uniform(-1, 1, shape).astype(dtype),
                        ctx=ctx)
    density = 0.5 if density is None else density
    dense = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.uniform(0, 1, shape) < density
    dense = dense * mask
    from .ndarray import sparse as sp
    if stype == "row_sparse":
        return sp.cast_storage(nd_array(dense), "row_sparse")
    if stype == "csr":
        return sp.cast_storage(nd_array(dense), "csr")
    raise MXNetError("unknown stype %r" % stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    ex = sym.bind(ctx=ctx, args={k: nd_array(v) for k, v in inputs.items()})
    outs = [o.asnumpy() for o in ex.forward(is_train=is_train)]
    return outs if len(outs) > 1 else outs[0]


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Finite differences vs symbolic backward
    (parity: test_utils.check_numeric_gradient:789). Like the reference,
    the random projection is part of the graph (sum(out * proj) wrapped in
    MakeLoss) so loss-style ops with fixed backward semantics are handled
    uniformly."""
    from . import symbol as _sym
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    location = {k: np.asarray(v, np.float64).astype(np.float32)
                for k, v in location.items()}
    if grad_nodes is None:
        grad_nodes = arg_names

    if len(sym.list_outputs()) > 1:
        raise MXNetError("check_numeric_gradient expects single output")
    proj = _sym.Variable("__random_proj")
    loss = _sym.MakeLoss(_sym.sum(sym * proj))

    # shapes: forward once to get output shape for the projection
    probe = sym.bind(ctx=ctx, args={k: nd_array(v, ctx=ctx)
                                    for k, v in location.items()},
                     aux_states={k: nd_array(v) for k, v in
                                 (aux_states or {}).items()} or None)
    out_shape = probe.forward()[0].shape
    head = np.random.normal(0, 1, out_shape).astype(np.float32)
    location = dict(location)
    location["__random_proj"] = head

    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    grads = {k: nd_zeros(v.shape, ctx=ctx) for k, v in location.items()
             if k in grad_nodes}
    ex = loss.bind(ctx=ctx, args=args, args_grad=grads,
                   aux_states={k: nd_array(v) for k, v in
                               (aux_states or {}).items()} or None)
    ex.forward(is_train=True)
    ex.backward()
    sym_grads = {k: grads[k].asnumpy() for k in grads}

    def f(loc):
        ex2 = loss.bind(ctx=ctx, args={k: nd_array(v, ctx=ctx)
                                       for k, v in loc.items()},
                        aux_states={k: nd_array(v) for k, v in
                                    (aux_states or {}).items()} or None)
        o = ex2.forward(is_train=use_forward_train)[0].asnumpy()
        return float(np.sum(o))

    for name in grad_nodes:
        base = location[name]
        num_grad = np.zeros_like(base)
        flat = base.reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = f(location)
            flat[i] = orig - numeric_eps
            fm = f(location)
            flat[i] = orig
            ng_flat[i] = (fp - fm) / (2 * numeric_eps)
        assert_almost_equal(num_grad, sym_grads[name], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("numeric_%s" % name, "symbolic_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=1e-20,
                           aux_states=None, ctx=None):
    """(parity: test_utils.check_symbolic_forward:921)"""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    ex = sym.bind(ctx=ctx, args=args,
                  aux_states={k: nd_array(v) for k, v in
                              (aux_states or {}).items()} or None)
    outputs = [o.asnumpy() for o in ex.forward()]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=1e-20, aux_states=None, grad_req="write",
                            ctx=None):
    """(parity: test_utils.check_symbolic_backward)"""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    args = {k: nd_array(v, ctx=ctx) for k, v in location.items()}
    grads = {k: nd_zeros(np.asarray(v).shape, ctx=ctx)
             for k, v in location.items()}
    ex = sym.bind(ctx=ctx, args=args, args_grad=grads, grad_req=grad_req)
    ex.forward(is_train=True)
    ex.backward(out_grads=[nd_array(g, ctx=ctx) for g in out_grads])
    for name, exp in expected.items():
        assert_almost_equal(grads[name].asnumpy(), exp, rtol=rtol, atol=atol,
                            names=("grad_%s" % name, "expected_%s" % name))
    return {k: v.asnumpy() for k, v in grads.items()}


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, rtol=1e-3, atol=1e-4):
    """Run the same graph on several contexts and compare outputs+grads —
    the cross-backend oracle (parity: test_utils.check_consistency; the
    reference compares cpu vs gpu, here cpu vs tpu).

    Default tolerance matches the reference's fp32 cross-backend tol of
    1e-3 (reference python/mxnet/test_utils.py:1267 `tol[np.float32]`).
    TPU transcendental units (tanh/exp are polynomial/exp2 hardware
    approximations) differ from CPU libm by up to a few e-5 absolute for
    O(1) inputs — correct behavior, not a precision bug — so the round-2
    atol of 1e-5 was miscalibrated for a cross-backend oracle."""
    if len(ctx_list) < 2:
        raise MXNetError("need at least two contexts")
    results = []
    np.random.seed(0)
    arg_shapes = None
    for spec in ctx_list:
        ctx = spec["ctx"] if isinstance(spec, dict) else spec
        shapes = {k: v for k, v in spec.items() if k != "ctx"} \
            if isinstance(spec, dict) else {}
        ex = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
        if arg_shapes is None:
            arg_shapes = {k: a.shape for k, a in ex.arg_dict.items()}
            arg_params = arg_params or {
                k: np.random.normal(0, scale, s).astype(np.float32)
                for k, s in arg_shapes.items()}
        for k, v in arg_params.items():
            ex.arg_dict[k][:] = v
        outs = [o.asnumpy() for o in ex.forward(is_train=True)]
        ex.backward()
        grads = {k: g.asnumpy() for k, g in ex.grad_dict.items()
                 if g is not None}
        results.append((outs, grads))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for o, r in zip(outs, ref_outs):
            assert_almost_equal(o, r, rtol=rtol, atol=atol)
        for k in ref_grads:
            assert_almost_equal(grads[k], ref_grads[k], rtol=rtol, atol=atol)
    return results


# -- remaining reference test_utils surface (test_utils.py parity) ----------

def default_dtype():
    """(parity: test_utils.default_dtype)"""
    return np.float32


def get_atol(atol=None):
    """(parity: test_utils.get_atol)"""
    return 1e-20 if atol is None else atol


def get_rtol(rtol=None):
    """(parity: test_utils.get_rtol)"""
    return 1e-5 if rtol is None else rtol


def random_sample(population, k):
    """Sample k without replacement (parity: test_utils.random_sample)."""
    import random as _random
    population_copy = population[:]
    _random.shuffle(population_copy)
    return population_copy[0:k]


def shuffle_csr_column_indices(csr):
    """Shuffle indices within each row (parity: the reference helper —
    exercises unsorted-column-index handling)."""
    import random as _random
    row_count = len(csr.indptr) - 1
    col_indices = csr.indices.asnumpy().copy()
    for i in range(row_count):
        start = int(csr.indptr[i].asnumpy()) \
            if hasattr(csr.indptr[i], "asnumpy") else int(csr.indptr[i])
        end = int(csr.indptr[i + 1].asnumpy()) \
            if hasattr(csr.indptr[i + 1], "asnumpy") else int(csr.indptr[i + 1])
        sublist = col_indices[start:end].tolist()
        _random.shuffle(sublist)
        col_indices[start:end] = sublist
    from .ndarray import sparse as _sp
    return _sp.csr_matrix((csr.data.asnumpy(), col_indices,
                           csr.indptr.asnumpy()), shape=csr.shape)


def assign_each(the_input, function):
    """Apply function elementwise via numpy (parity: assign_each)."""
    out = np.vectorize(function)(_as_np(the_input)) \
        if function is not None else _as_np(the_input).copy()
    return np.asarray(out)


def assign_each2(input1, input2, function):
    """(parity: assign_each2)"""
    if function is None:
        return _as_np(input1).copy()
    return np.asarray(np.vectorize(function)(_as_np(input1),
                                             _as_np(input2)))


def rand_sparse_ndarray(shape, stype, density=None, dtype=np.float32,
                        **kwargs):
    """Random sparse NDArray + its dense view (parity:
    test_utils.rand_sparse_ndarray)."""
    arr = rand_ndarray(shape, stype=stype, density=density, dtype=dtype)
    dense = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
    return arr, dense


def create_sparse_array(shape, stype, data_init=None, rsp_indices=None,
                        dtype=np.float32, modifier_func=None,
                        shuffle_csr_indices=False, density=0.5):
    """(parity: test_utils.create_sparse_array)"""
    from .ndarray import sparse as _sp
    dense = np.zeros(shape, dtype=dtype)
    if data_init is not None:
        dense[:] = data_init
    else:
        mask = np.random.uniform(size=shape) < density
        dense = (np.random.uniform(size=shape) * mask).astype(dtype)
    if rsp_indices is not None and stype == "row_sparse":
        keep = np.zeros(shape[0], bool)
        keep[np.asarray(rsp_indices, np.int64)] = True
        dense[~keep] = 0
    if modifier_func is not None:
        dense = np.vectorize(modifier_func)(dense).astype(dtype)
    if stype == "row_sparse":
        return _sp.row_sparse_array(dense)
    if stype == "csr":
        return _sp.csr_matrix(dense)
    from .ndarray import array as _arr
    return _arr(dense)


def create_sparse_array_zd(shape, stype, density, data_init=None,
                           rsp_indices=None, dtype=np.float32,
                           modifier_func=None, shuffle_csr_indices=False):
    """create_sparse_array allowing zero density (parity:
    test_utils.create_sparse_array_zd)."""
    if density == 0:
        shape = (max(shape[0], 1),) + tuple(shape[1:])
    return create_sparse_array(shape, stype, data_init=data_init,
                               rsp_indices=rsp_indices, dtype=dtype,
                               modifier_func=modifier_func,
                               density=density)


def rand_shape_nd(num_dim, dim=10):
    """(parity: test_utils.rand_shape_nd)"""
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reference-style reduce with axis/keepdims normalisation (parity:
    test_utils.np_reduce)."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=None, atol=None):
    """Index/value of the worst |a-b| violation (parity:
    test_utils.find_max_violation)."""
    rtol = get_rtol(rtol)
    atol = get_atol(atol)
    a, b = _as_np(a), _as_np(b)
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = np.unravel_index(np.argmax(violation), violation.shape)
    return loc, float(violation[loc])


def same(a, b):
    """(parity: test_utils.same) exact array equality."""
    return np.array_equal(_as_np(a), _as_np(b))


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """(parity: test_utils.almost_equal_ignore_nan)"""
    a, b = _as_np(a).copy(), _as_np(b).copy()
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return almost_equal(a, b, get_rtol(rtol), get_atol(atol))


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None,
                                   names=("a", "b")):
    """(parity: test_utils.assert_almost_equal_ignore_nan)"""
    a, b = _as_np(a).copy(), _as_np(b).copy()
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    assert_almost_equal(a, b, get_rtol(rtol), get_atol(atol), names=names)


def assert_exception(f, exception_type, *args, **kwargs):
    """(parity: test_utils.assert_exception)"""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("%s did not raise %s" % (f, exception_type))


def retry(n):
    """Decorator: retry a flaky (random) test n times (parity:
    test_utils.retry)."""
    assert n > 0

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError as e:
                    if i == n - 1:
                        raise e
        return wrapper
    return decorate


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients of an executor's scalar-summed output
    (parity: test_utils.numeric_grad — the engine under
    check_numeric_gradient)."""
    approx_grads = {}
    for name, arr in location.items():
        base = np.asarray(arr, np.float64).copy()
        grad = np.zeros_like(base)
        flat = base.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            old = flat[i]
            flat[i] = old + eps / 2
            executor.arg_dict[name][:] = base.reshape(arr.shape) \
                .astype(np.float32)
            f_plus = sum(float(o.asnumpy().sum())
                         for o in executor.forward(
                             is_train=use_forward_train))
            flat[i] = old - eps / 2
            executor.arg_dict[name][:] = base.reshape(arr.shape) \
                .astype(np.float32)
            f_minus = sum(float(o.asnumpy().sum())
                          for o in executor.forward(
                              is_train=use_forward_train))
            gflat[i] = (f_plus - f_minus) / eps
            flat[i] = old
        executor.arg_dict[name][:] = base.reshape(arr.shape) \
            .astype(np.float32)
        approx_grads[name] = grad
    return approx_grads


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Time forward(+backward) of a symbol (parity:
    test_utils.check_speed)."""
    import time as _time
    ctx = ctx or default_context()
    if grad_req is None:
        grad_req = "write"
    if location is None:
        location = {name: np.random.normal(size=shape, scale=1.0)
                    for name, shape in
                    zip(sym.list_arguments(),
                        sym.infer_shape(**kwargs)[0])}
    exe = sym.simple_bind(grad_req=grad_req, ctx=ctx,
                          **{k: v.shape for k, v in location.items()})
    for name, value in location.items():
        exe.arg_dict[name][:] = value
    exe.forward(is_train=True)       # materialise output shapes
    out_grads = [nd_array(np.random.normal(size=o.shape))
                 for o in exe.outputs]

    if typ == "whole":
        exe.forward(is_train=True)
        exe.backward(out_grads=out_grads)
        [o.asnumpy() for o in exe.outputs]
        tic = _time.time()
        for _ in range(N):
            exe.forward(is_train=True)
            exe.backward(out_grads=out_grads)
        [o.asnumpy() for o in exe.outputs]
        return (_time.time() - tic) / N
    if typ == "forward":
        exe.forward(is_train=False)
        [o.asnumpy() for o in exe.outputs]
        tic = _time.time()
        for _ in range(N):
            exe.forward(is_train=False)
        [o.asnumpy() for o in exe.outputs]
        return (_time.time() - tic) / N
    raise ValueError("typ must be 'whole' or 'forward'")


def list_gpus():
    """Indices of visible accelerator devices (parity:
    test_utils.list_gpus — CUDA_VISIBLE ≙ the attached TPU chips)."""
    import jax
    try:
        return list(range(len([d for d in jax.devices()
                               if d.platform != "cpu"])))
    except RuntimeError:
        return []


def download(url, fname=None, dirname=None, overwrite=False):
    """Download a file (parity: test_utils.download). This environment is
    zero-egress, so only file:// URIs and existing local paths resolve."""
    import os as _os
    import shutil as _shutil
    fname = fname or url.split("/")[-1]
    if dirname is not None:
        _os.makedirs(dirname, exist_ok=True)
        fname = _os.path.join(dirname, fname)
    if _os.path.exists(fname) and not overwrite:
        return fname
    if url.startswith("file://"):
        _shutil.copyfile(url[7:], fname)
        return fname
    if _os.path.exists(url):
        _shutil.copyfile(url, fname)
        return fname
    raise IOError("download: no network egress; provide a local path "
                  "(got %r)" % url)


def get_mnist(path=None):
    """MNIST as numpy dict (parity: test_utils.get_mnist). Reads the idx
    files from ``path`` (or MXTPU_MNIST_PATH); generates a deterministic
    synthetic stand-in when absent so tests stay hermetic."""
    import os as _os
    path = path or _os.environ.get("MXTPU_MNIST_PATH")
    if path and _os.path.exists(_os.path.join(path,
                                              "train-images-idx3-ubyte")):
        from .io import _read_idx_images, _read_idx_labels
        tr_i = _read_idx_images(_os.path.join(
            path, "train-images-idx3-ubyte")) / 255.0
        tr_l = _read_idx_labels(_os.path.join(
            path, "train-labels-idx1-ubyte"))
        te_i = _read_idx_images(_os.path.join(
            path, "t10k-images-idx3-ubyte")) / 255.0
        te_l = _read_idx_labels(_os.path.join(
            path, "t10k-labels-idx1-ubyte"))
    else:
        rs = np.random.RandomState(42)
        tr_i = rs.uniform(size=(512, 28, 28)).astype(np.float32)
        tr_l = rs.randint(0, 10, 512).astype(np.float32)
        te_i = rs.uniform(size=(128, 28, 28)).astype(np.float32)
        te_l = rs.randint(0, 10, 128).astype(np.float32)
    return {"train_data": tr_i.reshape(-1, 1, 28, 28),
            "train_label": tr_l,
            "test_data": te_i.reshape(-1, 1, 28, 28),
            "test_label": te_l}


def get_bz2_data(data_dir, data_name, url, data_origin_name):
    """(parity: test_utils.get_bz2_data) zero-egress: decompress a local
    .bz2 only."""
    import bz2 as _bz2
    import os as _os
    path = _os.path.join(data_dir, data_name)
    origin = _os.path.join(data_dir, data_origin_name)
    if not _os.path.exists(path):
        if not _os.path.exists(origin):
            raise IOError("get_bz2_data: no egress; place %s locally"
                          % data_origin_name)
        with _bz2.BZ2File(origin) as f, open(path, "wb") as out:
            out.write(f.read())
    return path


def set_env_var(key, val, default_val=""):
    """Set env var, returning its previous value (parity:
    test_utils.set_env_var)."""
    import os as _os
    prev_val = _os.environ.get(key, default_val)
    _os.environ[key] = val
    return prev_val


def same_array(array1, array2):
    """True when two NDArrays share storage (parity:
    test_utils.same_array — mutate-and-compare probe)."""
    array1[:] = array1.asnumpy() + 1
    if not same(array1.asnumpy(), array2.asnumpy()):
        array1[:] = array1.asnumpy() - 1
        return False
    array1[:] = array1.asnumpy() - 1
    return same(array1.asnumpy(), array2.asnumpy())


class discard_stderr:
    """Context manager silencing stderr (parity:
    test_utils.discard_stderr)."""

    def __enter__(self):
        import os as _os
        import sys as _sys
        self.stderr_fileno = _sys.stderr.fileno()
        self.old_stderr = _os.dup(self.stderr_fileno)
        self.bin_log_file = open(_os.devnull, "wb")
        _os.dup2(self.bin_log_file.fileno(), self.stderr_fileno)
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        import os as _os
        _os.dup2(self.old_stderr, self.stderr_fileno)
        self.bin_log_file.close()
        _os.close(self.old_stderr)
