"""Code-generation of the ``sym.*`` operator namespace.

Parity: reference ``python/mxnet/symbol/register.py``.
"""
from __future__ import annotations

from ..ops import registry as _registry
from .symbol import Symbol, _create


def make_sym_func(op):
    arg_names = op.arg_names

    def generic_op(*args, **kwargs):
        name = kwargs.pop("name", None)
        inputs = []
        i = 0
        while i < len(args) and isinstance(args[i], Symbol):
            inputs.append(args[i])
            i += 1
        # trailing positional values map onto params in declaration order
        param_order = list(op.defaults)
        for j, val in enumerate(args[i:]):
            if j < len(param_order):
                kwargs.setdefault(param_order[j], val)
        if op.nin != -1:
            for an in arg_names[len(inputs):]:
                if an in kwargs and isinstance(kwargs[an], Symbol):
                    inputs.append(kwargs.pop(an))
                elif any(isinstance(kwargs.get(a), Symbol)
                         for a in arg_names[len(inputs) + 1:]):
                    # a later named input is a Symbol: placeholder variable
                    from .symbol import Variable
                    inputs.append(Variable("%s_%s" % (name or op.name.lower(), an)))
                else:
                    break
        return _create(op.name, inputs, kwargs, name=name)

    generic_op.__name__ = op.name
    generic_op.__doc__ = op.doc or ("%s symbolic operator" % op.name)
    return generic_op


def populate(namespace):
    for name in _registry.list_ops():
        op = _registry.get_op(name)
        namespace[name] = make_sym_func(op)
    return namespace
