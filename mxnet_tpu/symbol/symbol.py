"""Symbol — declarative graph construction.

Parity: reference ``python/mxnet/symbol/symbol.py`` over nnvm::Symbol
(SURVEY.md §2.1 "nnvm equivalent"). TPU-native design: a Symbol is a
light Python DAG of op nodes; binding it compiles the WHOLE graph into a
single jitted XLA computation (see executor.py) — the nnvm pass pipeline
(PlanMemory, inplace detection, op fusion into engine bulks) is exactly
what XLA's compiler does better on TPU, so there is no separate IR.

JSON serialization keeps the reference's node-list format
(``nodes``/``arg_nodes``/``heads``) so saved graphs look familiar and
round-trip; op names and kwargs match the reference registry.
"""
from __future__ import annotations

import json

import numpy as np

from ..base import MXNetError
from ..name import NameManager
from ..attribute import AttrScope
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class _SymNode:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "_extra_attrs")

    def __init__(self, op, name, attrs, inputs):
        self.op = op            # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs)        # op params (strings/values)
        self.inputs = list(inputs)      # list of (node, out_index)
        self._extra_attrs = {}          # user attrs (__shape__, lr_mult…)

    def num_outputs(self):
        if self.op is None:
            return 1
        if self.op.nout == -1:  # SliceChannel-style: from params
            return int(self.attrs.get("num_outputs", 1))
        vis = self.op.visible_outputs
        if callable(vis):
            params = dict(self.op.defaults)
            params.update(self.attrs)
            vis = vis(params)
        return vis or self.op.nout


class Symbol:
    """An immutable handle on a list of node outputs."""

    def __init__(self, outputs):
        self._outputs = list(outputs)   # list of (node, out_index)

    # -- composition helpers ----------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._outputs)
        return "<Symbol %s>" % names

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output %r not found in %s" % (index, names))
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # -- graph traversal ---------------------------------------------------
    def _topo_nodes(self):
        """All nodes in topological order."""
        order, seen = [], set()
        stack = [n for n, _ in self._outputs]
        # iterative post-order
        visit = [(n, False) for n in reversed(stack)]
        while visit:
            node, done = visit.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            visit.append((node, True))
            for child, _ in reversed(node.inputs):
                if id(child) not in seen:
                    visit.append((child, False))
        return order

    def _aux_var_ids(self):
        """Variables used only in aux positions (BatchNorm moving stats)."""
        aux, non_aux = set(), set()
        for node in self._topo_nodes():
            if node.op is None:
                continue
            aux_idx = set(node.op.aux_inputs)
            for i, (child, _) in enumerate(node.inputs):
                if child.op is None:
                    (aux if i in aux_idx else non_aux).add(id(child))
        return aux - non_aux

    def list_arguments(self):
        """Input variable names, topo order (parity: Symbol.list_arguments)."""
        aux_ids = self._aux_var_ids()
        return [n.name for n in self._topo_nodes()
                if n.op is None and id(n) not in aux_ids]

    def list_auxiliary_states(self):
        aux_ids = self._aux_var_ids()
        return [n.name for n in self._topo_nodes()
                if n.op is None and id(n) in aux_ids]

    def list_outputs(self):
        outs = []
        for node, idx in self._outputs:
            if node.op is None:
                outs.append(node.name)
            elif node.num_outputs() == 1:
                outs.append(node.name + "_output")
            else:
                outs.append("%s_output%d" % (node.name, idx))
        return outs

    def get_internals(self):
        """Symbol exposing every node's outputs (parity: get_internals)."""
        outs = []
        for node in self._topo_nodes():
            for i in range(node.num_outputs()):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        inputs = []
        for node, _ in self._outputs:
            inputs.extend(node.inputs)
        return Symbol(inputs) if inputs else None

    # -- attrs -------------------------------------------------------------
    def attr(self, key):
        node = self._outputs[0][0]
        return node._extra_attrs.get(key)

    def _set_attr(self, **kwargs):
        node = self._outputs[0][0]
        node._extra_attrs.update(kwargs)

    def list_attr(self, recursive=False):
        """Attributes of this node (parity: symbol.list_attr:570; use
        attr_dict() for the recursive per-node view)."""
        if recursive:
            raise MXNetError("list_attr(recursive=True) was removed in the "
                             "reference too; use attr_dict() instead")
        node = self._outputs[0][0]
        out = {k: str(v) for k, v in node._extra_attrs.items()}
        return out

    def list_inputs(self):
        """All arguments and auxiliary states (parity:
        symbol.list_inputs:786)."""
        return self.list_arguments() + self.list_auxiliary_states()

    def debug_str(self):
        """Human-readable graph dump (parity: symbol.debug_str:1108)."""
        lines = []
        for node in self._topo_nodes():
            if node.op is None:
                lines.append("Variable:%s" % node.name)
            else:
                ins = ", ".join(n.name for n, _ in node.inputs)
                attrs = "".join(", %s=%r" % kv
                                for kv in sorted(node.attrs.items()))
                lines.append("Op:%s, Name=%s\n  Inputs: %s%s"
                             % (node.op.name, node.name, ins, attrs))
        return "\n".join(lines) + "\n"

    def gradient(self, wrt):
        """(parity: symbol.gradient:1676 — unimplemented in the reference
        as well; use simple_bind + backward or autograd)"""
        raise MXNetError("symbol.gradient is not implemented (the "
                         "reference raises too); use executor backward "
                         "or autograd")

    def attr_dict(self):
        out = {}
        for node in self._topo_nodes():
            d = {}
            d.update({k: str(v) for k, v in node.attrs.items()})
            d.update(node._extra_attrs)
            if d:
                out[node.name] = d
        return out

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """(parity: Symbol.infer_shape) returns (arg_shapes, out_shapes,
        aux_shapes); unknown arg shapes are inferred via the op hooks +
        jax.eval_shape (see executor._GraphProgram)."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        from ..executor import infer_graph_shapes
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        return infer_graph_shapes(self, known, partial=partial)

    def infer_type(self, *args, **kwargs):
        """(parity: Symbol.infer_type / reference InferType pass) returns
        (arg_types, out_types, aux_types). Dtypes propagate through the
        graph via the joint attr-inference pass (executor.infer_graph_attrs);
        shapes come from Variable ``__shape__`` attrs where present — ops
        whose shapes stay unknown report None, as infer_shape_partial does.
        """
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, dt in zip(arg_names, args):
                if dt is not None:
                    known[name] = np.dtype(dt)
        known.update({k: np.dtype(v) for k, v in kwargs.items()
                      if v is not None})
        from ..executor import infer_graph_attrs
        res = infer_graph_attrs(self, {}, known_types=known, partial=True)
        return res[3], res[4], res[5]

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """Reference-compatible JSON node list (parity: nnvm SaveJSON)."""
        nodes = self._topo_nodes()
        node_id = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            entry = {
                "op": "null" if n.op is None else n.op.name,
                "name": n.name,
                "inputs": [[node_id[id(c)], idx, 0] for c, idx in n.inputs],
            }
            attrs = {k: str(v) for k, v in n.attrs.items()}
            attrs.update(n._extra_attrs)
            if attrs:
                entry["attrs"] = attrs
            out_nodes.append(entry)
        graph = {
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.op is None],
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": [[node_id[id(n)], idx, 0] for n, idx in self._outputs],
            "attrs": {"mxnet_version": ["int", 1200]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname):
        from ..filesystem import open_uri
        with open_uri(fname, "w") as f:
            f.write(self.tojson())

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Allocate arrays and bind (parity: symbol.py simple_bind:1254)."""
        from ..executor import Executor
        return Executor._simple_bind(self, ctx, grad_req, type_dict, kwargs,
                                     group2ctx=group2ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """Bind with existing arrays (parity: symbol.py bind:1518).
        ``shared_exec`` shares the donor executor's compiled-program
        cache — a rebind at a new shape reuses every signature already
        compiled (the reference shared memory; here we share programs)."""
        from ..executor import Executor
        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states, group2ctx=group2ctx,
                              shared_exec=shared_exec)

    # -- eval / call -------------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx=ctx, args=kwargs)
        return ex.forward()

    def __call__(self, *args, **kwargs):
        """Compose: replace free variables with given symbols (parity:
        Symbol composition)."""
        name = kwargs.pop("name", None)
        mapping = {}
        arg_names = self.list_arguments()
        for n, s in zip(arg_names, args):
            mapping[n] = s
        mapping.update(kwargs)
        for k, v in mapping.items():
            if not isinstance(v, Symbol):
                raise MXNetError("compose expects Symbols")
        return self._compose(mapping)

    def _compose(self, mapping):
        memo = {}

        def clone(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.op is None and node.name in mapping:
                new = mapping[node.name]._outputs[0][0]
            else:
                new = _SymNode(node.op, node.name, node.attrs,
                               [(clone(c), i) for c, i in node.inputs])
                new._extra_attrs = dict(node._extra_attrs)
            memo[id(node)] = new
            return new

        return Symbol([(clone(n), i) for n, i in self._outputs])

    # -- operators ---------------------------------------------------------
    def _binary(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            lhs, rhs = (other, self) if reverse else (self, other)
            return _create(op_name, [lhs, rhs], {})
        if isinstance(other, (int, float)):
            return _create(scalar_op, [self], {"scalar": other})
        raise TypeError("unsupported operand %r" % (other,))

    def __add__(self, other):
        return self._binary(other, "elemwise_add" if isinstance(other, Symbol)
                            else "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _create("_rminus_scalar", [self], {"scalar": other})

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _create("_rdiv_scalar", [self], {"scalar": other})

    __div__ = __truediv__

    def __pow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", [self], {})

    # convenience mirrors of NDArray methods
    def reshape(self, shape, **kw):
        return _create("Reshape", [self], {"shape": shape, **kw})

    def sum(self, **kw):
        return _create("sum", [self], kw)

    def mean(self, **kw):
        return _create("mean", [self], kw)

    def flatten(self):
        return _create("Flatten", [self], {})

    def transpose(self, axes=()):
        return _create("transpose", [self], {"axes": axes})

    def slice_axis(self, axis, begin, end):
        return _create("slice_axis", [self], {"axis": axis, "begin": begin,
                                              "end": end})

    def astype(self, dtype):
        return _create("Cast", [self], {"dtype": dtype})


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (parity: mx.sym.Variable)."""
    node = _SymNode(None, name, {}, [])
    extra = dict(AttrScope.current.get(attr))
    if shape is not None:
        extra["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        extra["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        extra["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        extra["__dtype__"] = str(np.dtype(dtype))
    if init is not None:
        extra["__init__"] = init if isinstance(init, str) else init.dumps()
    node._extra_attrs = extra
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol (parity: mx.sym.Group)."""
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _create(op_name, input_syms, kwargs, name=None):
    """Create an op node (used by the generated sym.* functions)."""
    op = _registry.get_op(op_name)
    kwargs = dict(kwargs)
    name = name or kwargs.pop("name", None)
    attr = AttrScope.current.get(kwargs.pop("attr", None))
    kwargs.pop("out", None)
    inputs = []
    for s in input_syms:
        if len(s._outputs) != 1:
            if op.nin == -1:
                inputs.extend(s._outputs)
                continue
            raise MXNetError("op %s expects single-output inputs" % op_name)
        inputs.append(s._outputs[0])
    name = NameManager.current.get(name, op.name.lower().lstrip("_"))
    # auto-create variables for missing learnable inputs (e.g. weight/bias
    # when calling sym.Convolution(data, kernel=..) without weight=)
    if op.nin not in (-1, 0) and len(inputs) < op.nin:
        needed = op.nin - len(inputs)
        no_bias = kwargs.get("no_bias", op.defaults.get("no_bias", False))
        for ai in range(len(inputs), op.nin):
            arg_name = op.arg_names[ai] if ai < len(op.arg_names) else "arg%d" % ai
            if no_bias and arg_name == "bias":
                continue
            if op.name == "LeakyReLU" and kwargs.get(
                    "act_type", op.defaults.get("act_type")) != "prelu":
                continue
            if op.name in ("SequenceLast", "SequenceMask", "SequenceReverse") \
                    and not kwargs.get("use_sequence_length", False):
                continue
            full = "%s_%s" % (name, arg_name)
            inputs.append((Variable(full)._outputs[0]))
    node = _SymNode(op, name, kwargs, inputs)
    if attr:
        node._extra_attrs.update(attr)
    n_out = node.num_outputs()
    return Symbol([(node, i) for i in range(n_out)])


def load_json(json_str):
    """Load a symbol from reference-format JSON, upgrading legacy layouts
    (parity: sym.load_json + src/nnvm/legacy_json_util.cc). Handled
    versions: modern ``attrs``, 0.9-era ``attr``, pre-0.9 ``param``.
    Non-parameter attributes a legacy graph stored alongside op params
    (``lr_mult``/``wd_mult``/``force_mirroring``/user attrs) migrate to
    ``__k__`` extra attrs instead of reaching the op function — the
    upgrade pass the reference runs before attr parsing
    (legacy_json_util.cc:29-96)."""
    graph = json.loads(json_str)
    nodes = []
    for entry in graph["nodes"]:
        attrs = entry.get("attrs") or entry.get("attr") or \
            entry.get("param") or {}
        extra = {k: v for k, v in attrs.items() if k.startswith("__")}
        params = {k: _parse_attr(v) for k, v in attrs.items()
                  if not k.startswith("__")}
        if entry["op"] == "null":
            node = _SymNode(None, entry["name"], {}, [])
            # legacy variable nodes kept lr_mult etc. as bare keys
            extra.update({"__%s__" % k: str(v) for k, v in params.items()})
            node._extra_attrs = extra
        else:
            op = _registry.get_op(entry["op"])
            accepted = op.accepted_params()
            unknown = [] if accepted is None else \
                [k for k in params if k not in accepted]
            for k in unknown:  # legacy non-parameter attrs -> __k__ form
                extra["__%s__" % k] = str(params.pop(k))
            inputs = [(nodes[i], idx) for i, idx, *_ in entry["inputs"]]
            node = _SymNode(op, entry["name"], params, inputs)
            node._extra_attrs = extra
        nodes.append(node)
    heads = graph.get("heads", [[len(nodes) - 1, 0, 0]])
    return Symbol([(nodes[i], idx) for i, idx, *_ in heads])


def _parse_attr(v):
    if not isinstance(v, str):
        return v
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        import ast
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def load(fname):
    from ..filesystem import open_uri
    with open_uri(fname, "r") as f:
        return load_json(f.read())
