"""The ``mx.sym`` namespace (parity: python/mxnet/symbol/__init__.py)."""
from .symbol import Symbol, Variable, var, Group, load, load_json
from . import register as _register

_register.populate(globals())

# creation helpers mirroring mx.sym.zeros/ones (build graphs around consts)
def zeros(shape, dtype="float32", **kwargs):
    return globals()["_zeros"](shape=shape, dtype=dtype, **kwargs)


def ones(shape, dtype="float32", **kwargs):
    return globals()["_ones"](shape=shape, dtype=dtype, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", **kwargs):
    return globals()["_arange"](start=start, stop=stop, step=step,
                                repeat=repeat, dtype=dtype, **kwargs)

from . import contrib  # noqa: E402,F401
from . import sparse   # noqa: E402,F401


# module-level symbol helpers (parity: symbol.py:2179-2287)
def pow(base, exp):
    """(parity: mx.sym.pow)"""
    from .symbol import Symbol as _S
    if isinstance(base, _S) and isinstance(exp, _S):
        return base.__pow__(exp)
    if isinstance(base, _S):
        return base ** exp
    if isinstance(exp, _S):
        return globals()["_rpower_scalar"](exp, scalar=base)
    return base ** exp


def maximum(left, right):
    """(parity: mx.sym.maximum)"""
    from .symbol import Symbol as _S
    if isinstance(left, _S) and isinstance(right, _S):
        return globals()["broadcast_maximum"](left, right)
    if isinstance(left, _S):
        return globals()["_maximum_scalar"](left, scalar=right)
    if isinstance(right, _S):
        return globals()["_maximum_scalar"](right, scalar=left)
    return left if left > right else right


def minimum(left, right):
    """(parity: mx.sym.minimum)"""
    from .symbol import Symbol as _S
    if isinstance(left, _S) and isinstance(right, _S):
        return globals()["broadcast_minimum"](left, right)
    if isinstance(left, _S):
        return globals()["_minimum_scalar"](left, scalar=right)
    if isinstance(right, _S):
        return globals()["_minimum_scalar"](right, scalar=left)
    return left if left < right else right


def hypot(left, right):
    """(parity: mx.sym.hypot)"""
    from .symbol import Symbol as _S
    if isinstance(left, _S) and isinstance(right, _S):
        return globals()["broadcast_hypot"](left, right)
    if isinstance(left, _S):
        return globals()["_hypot_scalar"](left, scalar=right)
    if isinstance(right, _S):
        return globals()["_hypot_scalar"](right, scalar=left)
    import math
    return math.hypot(left, right)


def full(shape, val, dtype=None):
    """(parity: mx.sym.full) — a constant-filled symbol."""
    return globals()["_full"](shape=shape, value=float(val),
                              dtype=dtype or "float32") \
        if "_full" in globals() else \
        globals()["zeros"](shape=shape, dtype=dtype or "float32") + val


# fluent methods (x.relu() == mx.sym.relu(x)) + the reference's
# explicitly-unsupported NDArray-only stubs (symbol.py raises
# NotImplementedForSymbol for these)
from ..ndarray import _FLUENT_METHODS as _FLUENT, _attach_fluent  # noqa: E402
_attach_fluent(Symbol, globals(), _FLUENT)


def _not_for_symbol(name):
    def method(self, *args, **kwargs):
        from ..base import MXNetError
        raise MXNetError("operation %s is not supported for Symbol "
                         "(parity: symbol.py NotImplementedForSymbol)"
                         % name)
    method.__name__ = name
    return method


for _name in ["wait_to_read", "asnumpy", "asscalar", "copy",
              "as_in_context", "detach", "backward"]:
    if not hasattr(Symbol, _name):
        setattr(Symbol, _name, _not_for_symbol(_name))
del _name
