"""The ``mx.sym`` namespace (parity: python/mxnet/symbol/__init__.py)."""
from .symbol import Symbol, Variable, var, Group, load, load_json
from . import register as _register

_register.populate(globals())

# creation helpers mirroring mx.sym.zeros/ones (build graphs around consts)
def zeros(shape, dtype="float32", **kwargs):
    return globals()["_zeros"](shape=shape, dtype=dtype, **kwargs)


def ones(shape, dtype="float32", **kwargs):
    return globals()["_ones"](shape=shape, dtype=dtype, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", **kwargs):
    return globals()["_arange"](start=start, stop=stop, step=step,
                                repeat=repeat, dtype=dtype, **kwargs)

from . import contrib  # noqa: E402,F401
from . import sparse   # noqa: E402,F401
