"""Resource manager — per-device temp-space and PRNG resources.

Parity: reference ``include/mxnet/resource.h:37-185`` + ``src/
resource.cc``: ops request ``kTempSpace`` scratch buffers or ``kRandom``
PRNG states via ``ResourceManager::Get()->Request(ctx, req)``;
``MXNET_EXEC_NUM_TEMP`` bounds concurrent scratch copies.

TPU-native design: XLA allocates fused-kernel scratch itself, so
``temp_space`` exists for *host-visible* scratch (custom ops, IO) and is
a pooled Storage allocation; ``random`` hands out split jax PRNG keys
from the per-device stream — the functional analogue of the reference's
per-device PRNG state pool (seeded globally by ``mx.random.seed``, same
contract as ``resource.h`` kRandom).
"""
from __future__ import annotations

import threading

import numpy as np

from .base import MXNetError
from .context import current_context
from . import random as _random
from .storage import Storage

__all__ = ["Resource", "ResourceManager", "request"]


class Resource:
    """One granted resource (parity: struct Resource)."""

    _MAX_RETIRED = 4

    def __init__(self, kind, ctx):
        self.kind = kind
        self.ctx = ctx
        self._handle = None
        self._retired = []

    # -- kTempSpace --------------------------------------------------------
    def get_space(self, shape, dtype=np.float32):
        """Scratch numpy buffer, reused across requests of the same slot
        (parity: Resource::get_space — like the reference, a later larger
        request invalidates earlier views logically; the old buffer is
        parked — up to _MAX_RETIRED of them, then freed oldest-first —
        so recently-invalidated views never alias a re-issued pool
        buffer)."""
        if self.kind != "temp_space":
            raise MXNetError("get_space on a %r resource" % self.kind)
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if self._handle is None or self._handle.size < nbytes:
            if self._handle is not None:
                self._retired.append(self._handle)
                # park outgrown buffers so recent stale views never alias a
                # re-issued pool buffer, but bound the parking lot: views
                # older than the last _MAX_RETIRED grows are invalidated
                # (long-lived resources like an ImageIter slot never call
                # release(), and unbounded parking is a leak)
                while len(self._retired) > self._MAX_RETIRED:
                    Storage.get().free(self._retired.pop(0))
            self._handle = Storage.get().alloc(nbytes)
        return self._handle.array(shape, dtype)

    # -- kRandom -----------------------------------------------------------
    def get_key(self):
        """Fresh jax PRNG key split off the global stream
        (parity: Resource::get_random's per-call state)."""
        if self.kind != "random":
            raise MXNetError("get_key on a %r resource" % self.kind)
        return _random.take_key()

    def release(self):
        for h in self._retired:
            Storage.get().free(h)
        self._retired = []
        if self._handle is not None:
            Storage.get().free(self._handle)
            self._handle = None


class ResourceManager:
    """(parity: ResourceManager::Get()->Request)"""

    _instance = None
    _lock = threading.Lock()

    @staticmethod
    def get():
        with ResourceManager._lock:
            if ResourceManager._instance is None:
                ResourceManager._instance = ResourceManager()
        return ResourceManager._instance

    def __init__(self):
        from .base import get_env
        # number of temp-space slots handed out round-robin per device
        # (parity: MXNET_EXEC_NUM_TEMP, resource.cc)
        self._num_temp = int(get_env("MXNET_EXEC_NUM_TEMP", 1))
        self._temp = {}
        self._next = {}

    def request(self, ctx=None, req="temp_space"):
        ctx = ctx or current_context()
        key = (ctx.device_type, ctx.device_id)
        if req == "random":
            return Resource("random", ctx)
        if req != "temp_space":
            raise MXNetError("unknown resource request %r" % req)
        with ResourceManager._lock:
            slots = self._temp.setdefault(key, [])
            if len(slots) < self._num_temp:
                slots.append(Resource("temp_space", ctx))
                return slots[-1]
            i = self._next.get(key, 0)
            self._next[key] = (i + 1) % self._num_temp
            return slots[i]


def request(ctx=None, req="temp_space"):
    """Module-level convenience (parity: op FResourceRequest grants)."""
    return ResourceManager.get().request(ctx, req)
