"""Server node entry for distributed kvstore roles.

Parity: reference ``python/mxnet/kvstore_server.py`` — in the reference a
``DMLC_ROLE=server`` process enters ``KVStoreServer.run()`` and services
ps-lite push/pull RPCs until ``kStopServer``. TPU-native training is
single-program SPMD: every host runs the SAME program and gradients
reduce via XLA collectives, so there is no separate server role to host.
This module keeps the entry point so reference launch scripts work:

* ``DMLC_ROLE=worker`` / unset — no-op, returns immediately.
* ``DMLC_ROLE=server`` / ``scheduler`` — logs that the role is absorbed
  by SPMD collectives and exits 0, letting legacy launchers (which spawn
  worker+server+scheduler triples) run the worker processes unharmed.

Commands the reference server accepted (kController, optimizer blobs) are
decoded for diagnostics when received via ``send_command_to_servers``.
"""
from __future__ import annotations

import logging
import os
import pickle

from . import kvstore

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """The compatibility shell of the reference's server run-loop."""

    def __init__(self, kv):
        self.kvstore = kv
        self.handle = getattr(kv, "handle", None)
        self.init_logging = False

    def _controller(self):
        """Return the server controller (parity: kvstore_server.py:41)."""
        def server_controller(cmd_id, cmd_body, _):
            if not self.init_logging:
                header = "%(asctime)-15s Server[" + str(
                    self.kvstore.rank) + "]"
                logging.basicConfig(level=logging.DEBUG, format=header)
                self.init_logging = True
            if cmd_id == 0:
                try:
                    optimizer = pickle.loads(
                        cmd_body if isinstance(cmd_body, bytes)
                        else cmd_body.encode("latin1"))
                except Exception:  # diagnostics only
                    optimizer = cmd_body
                logging.info("server optimizer (applied worker-side under "
                             "SPMD): %s", optimizer)
            else:
                logging.info("server command %d ignored under SPMD", cmd_id)
        return server_controller

    def run(self):
        """Run the server loop.

        Under SPMD there are no RPCs to wait for — the method logs and
        returns so launcher-spawned server processes exit cleanly.
        """
        logging.info(
            "kvstore server role absorbed by XLA collectives (SPMD); "
            "nothing to serve — exiting run loop")


def _init_kvstore_server_module():
    """Start the server when this process was launched with a server role
    (parity: kvstore_server.py:75, called at import in the reference)."""
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role in ("server", "scheduler"):
        kv = kvstore.create("dist")
        server = KVStoreServer(kv)
        server.run()
        raise SystemExit(0)


# parity: the reference runs this at import so a DMLC_ROLE=server process
# never reaches user training code
_init_kvstore_server_module()
