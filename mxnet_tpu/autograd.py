"""Autograd: imperative differentiation scopes.

Parity: reference ``python/mxnet/autograd.py`` (record/pause/train_mode/
predict_mode/mark_variables/backward/grad/Function) backed by
``src/imperative/imperative.cc``. The tape lives in mxnet_tpu.imperative;
each recorded op stores its ``jax.vjp`` residual instead of an nnvm node.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from . import imperative as _imp

__all__ = ["record", "pause", "train_mode", "predict_mode", "mark_variables",
           "backward", "grad", "is_recording", "is_training",
           "set_recording", "set_training", "Function"]


is_recording = _imp.is_recording
is_training = _imp.is_training
set_recording = _imp.set_recording
set_training = _imp.set_training
mark_variables = _imp.mark_variables
get_symbol = _imp.get_symbol


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_record = is_record
        self._enter_train = train_mode
        self._prev_record = None
        self._prev_train = None

    def __enter__(self):
        if self._enter_record is not None:
            self._prev_record = set_recording(self._enter_record)
        if self._enter_train is not None:
            self._prev_train = set_training(self._enter_train)
        return self

    def __exit__(self, *exc):
        if self._enter_record is not None:
            set_recording(self._prev_record)
        if self._enter_train is not None:
            set_training(self._prev_train)


def record(train_mode=True):
    """Scope in which ops are recorded for backward (parity: autograd.record)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """(parity: autograd.backward)"""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    _imp.backward(list(heads), head_grads, retain_graph=retain_graph,
                  train_mode=train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables (parity: autograd.grad,
    higher-order capable via ``create_graph=True``).

    Gradients are returned rather than written into ``.grad``. With
    ``create_graph`` the backward is itself recorded: the tape subgraph
    is replayed as one pure jax function, its vjp produces the
    gradients, and that whole computation lands on the tape as a single
    differentiable node — so grad-of-grad composes to any order
    (jax owns the nested differentiation).
    """
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    single = not isinstance(variables, (list, tuple))
    varlist = [variables] if single else list(variables)
    if create_graph:
        out = _grad_create_graph(list(heads), varlist, head_grads,
                                 train_mode)
        return out[0] if single else out

    # stash existing grad state, attach temp buffers
    saved = [(v._grad, v._tape) for v in varlist]
    from .ndarray.ndarray import _wrap
    grads = [_wrap(jnp.zeros(v.shape, v._data.dtype), v._ctx) for v in varlist]
    for v, g in zip(varlist, grads):
        if v._tape is None or not isinstance(v._tape[0], _imp.Leaf):
            raise MXNetError("autograd.grad: variables must have attached grad "
                             "(call attach_grad before record)")
        v._grad = g
    try:
        _imp.backward(list(heads), head_grads,
                      retain_graph=bool(retain_graph), train_mode=train_mode)
        out = [v._grad for v in varlist]
    finally:
        for v, (g, t) in zip(varlist, saved):
            v._grad = g
            v._tape = t
    return out[0] if single else out


def _grad_create_graph(heads, varlist, head_grads, train_mode):
    """Differentiable gradients: replay the tape as a pure function and
    record its vjp as one new tape node."""
    import jax
    from .ndarray.ndarray import NDArray, _wrap

    for v in varlist:
        if v._tape is None or not isinstance(v._tape[0], _imp.Leaf):
            raise MXNetError("autograd.grad: variables must have attached "
                             "grad (call attach_grad before record)")
    # replay over EVERY leaf the subgraph touches, so the recorded grad
    # node keeps cross-derivatives w.r.t. variables not being asked for
    replay, leaves = _imp.build_pure_from_tape(heads)
    if head_grads is None:
        hg = tuple(jnp.ones(h.shape, h._data.dtype) for h in heads)
    else:
        hg = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                   for g in head_grads)
    want = []
    for v in varlist:
        leaf = v._tape[0]
        pos = next((i for i, l in enumerate(leaves) if l is leaf), None)
        if pos is None:
            raise MXNetError("autograd.grad: variable does not feed the "
                             "given heads")
        want.append(pos)

    def grad_fn(*leaf_raws):
        _, vjp = jax.vjp(replay, *leaf_raws)
        all_grads = vjp(hg)
        return tuple(all_grads[i] for i in want)

    leaf_raws = [l.array._data for l in leaves]
    outs, vjp2 = jax.vjp(grad_fn, *leaf_raws)
    node = _imp.TapeNode(
        [(l, 0) for l in leaves], vjp2,
        [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs], "_grad")
    node.pure_fn = grad_fn          # third order and beyond compose
    node.raw_inputs = leaf_raws
    results = []
    for i, o in enumerate(outs):
        nd = _wrap(o)
        nd._tape = (node, i)
        results.append(nd)
    return results


class Function:
    """User-defined differentiable function (parity: autograd.Function:364).

    Subclass and implement ``forward`` / ``backward`` over NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap

        was_recording = is_recording()
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        if was_recording:
            func = self

            def vjp_fn(out_cts):
                cts = [_wrap(c) for c in out_cts]
                with pause():
                    in_grads = func.backward(*cts)
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(g._data if isinstance(g, NDArray) else g
                             for g in in_grads)

            parents = [x._tape if isinstance(x, NDArray) and x._tape is not None
                       else None for x in inputs]
            node = _imp.TapeNode(
                parents, vjp_fn,
                [jax.ShapeDtypeStruct(o.shape, o._data.dtype) for o in outs],
                type(self).__name__)
            for i, o in enumerate(outs):
                o._tape = (node, i)
        return outputs
