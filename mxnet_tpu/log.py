"""Logging utilities (parity: reference ``python/mxnet/log.py``).

Colored level labels on TTYs, plain ``level:name:message`` otherwise.
"""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger",
           "CRITICAL", "DEBUG", "ERROR", "FATAL", "INFO", "NOTSET", "WARNING"]

CRITICAL = logging.CRITICAL
DEBUG = logging.DEBUG
ERROR = logging.ERROR
FATAL = logging.FATAL
INFO = logging.INFO
NOTSET = logging.NOTSET
WARNING = logging.WARNING

PY3 = True


class _Formatter(logging.Formatter):
    """Customized log formatter with colored level labels."""

    def __init__(self):
        datefmt = "%m%d %H:%M:%S"
        super().__init__(datefmt=datefmt)

    def _get_color(self, level):
        if logging.WARNING <= level:
            return "\x1b[31m"
        elif logging.INFO <= level:
            return "\x1b[32m"
        return "\x1b[34m"

    def _get_label(self, level):
        if level == logging.CRITICAL:
            return "C"
        elif level == logging.ERROR:
            return "E"
        elif level == logging.WARNING:
            return "W"
        elif level == logging.INFO:
            return "I"
        elif level == logging.DEBUG:
            return "D"
        return "U"

    def format(self, record):
        fmt = ""
        if sys.stderr.isatty():
            fmt += self._get_color(record.levelno)
        fmt += self._get_label(record.levelno)
        fmt += "%(asctime)s %(process)d %(pathname)s:%(funcName)s:%(lineno)d"
        fmt += "]"
        if sys.stderr.isatty():
            fmt += "\x1b[0m"
        fmt += " %(message)s"
        self._style._fmt = fmt
        return super().format(record)


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias of :func:`get_logger`."""
    import warnings
    warnings.warn("getLogger is deprecated, use get_logger instead",
                  DeprecationWarning)
    return get_logger(name, filename, filemode, level)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Get a customized logger; attaches one handler per logger name."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", None):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
        else:
            hdlr = logging.StreamHandler()
            # the `_Formatter` contain some escape character to
            # represent color, which is not suitable for FileHandler
            hdlr.setFormatter(_Formatter())
        logger.addHandler(hdlr)
        logger.setLevel(level)
    return logger
