"""URI-aware stream opening for save/load and RecordIO.

Parity: the reference's dmlc ``Stream::Create`` which dispatches on URI
scheme (local, ``s3://``, ``hdfs://`` — SURVEY.md §2.1 dmlc-core). The
TPU build is zero-egress, so remote schemes are a REGISTRY: ``file://``
and plain paths work out of the box; a deployment registers openers for
its object store (e.g. wrapping fsspec/gcsfs) with
:func:`register_scheme`, and every save/load/RecordIO call site goes
through :func:`open_uri` — the same one-dispatch-point design as
dmlc Stream.

    from mxnet_tpu import filesystem
    filesystem.register_scheme("s3", lambda uri, mode: s3fs.open(uri, mode))
    mx.nd.save("s3://bucket/weights.params", arrs)
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["open_uri", "register_scheme", "scheme_of"]

_OPENERS = {}


def scheme_of(uri):
    """Return the URI scheme, or "" for a plain local path. Windows drive
    letters ("C:\\...") and schemeless paths both map to ""."""
    if not isinstance(uri, (str, os.PathLike)):
        return ""
    s = os.fspath(uri)
    head, sep, _ = s.partition("://")
    if not sep or len(head) <= 1:
        return ""
    return head.lower()


def register_scheme(scheme, opener):
    """Register ``opener(uri, mode) -> file object`` for a URI scheme
    (parity: dmlc FileSystem registry)."""
    if not scheme or "://" in scheme:
        raise MXNetError("scheme must be a bare name like 's3'")
    _OPENERS[scheme.lower()] = opener


def open_uri(uri, mode="rb"):
    """Open a local path, file:// URI, or any registered scheme."""
    scheme = scheme_of(uri)
    uri = os.fspath(uri)
    if scheme in ("", "file"):
        path = uri[len("file://"):] if scheme == "file" else uri
        return open(path, mode)
    opener = _OPENERS.get(scheme)
    if opener is None:
        raise MXNetError(
            "no stream handler for %r URIs (zero-egress build): register "
            "one with mxnet_tpu.filesystem.register_scheme(%r, opener)"
            % (scheme, scheme))
    return opener(uri, mode)
