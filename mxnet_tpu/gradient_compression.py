"""2-bit gradient compression with error feedback.

Parity: reference ``src/kvstore/gradient_compression.{h,cc,cu}`` —
threshold quantisation (values >= +thr -> +thr, <= -thr -> -thr, else 0)
with the quantisation error kept in a per-key residual that is added to
the next gradient, so the signal is preserved over steps.

TPU-native design: the codes pack 16-per-uint32 with vectorised shift/or
(XLA fuses the whole quantise+pack into one elementwise kernel — a
hand-written Pallas pass adds nothing for a bandwidth-bound op). The
compressed payload is what crosses the slow link: `compressed_psum`
quantises per device, all-gathers the 16x-smaller packed words over the
mesh axis, and dequantise-sums locally — the SPMD analogue of the
reference's worker-quantise -> server-dequantise-aggregate path
(``kvstore_dist_server.h:173`` kCompressedPushPull).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError

__all__ = ["GradientCompression", "quantize_2bit", "dequantize_2bit",
           "compressed_psum"]

_CODES_PER_WORD = 16  # 2 bits each in a uint32


def _num_words(size):
    return -(-size // _CODES_PER_WORD)


def quantize_2bit(grad, residual, threshold=0.5):
    """Quantise ``grad + residual`` to 2-bit codes.

    Returns ``(packed, new_residual)`` where packed is uint32 of
    ``ceil(size/16)`` words and new_residual has grad's shape/dtype.
    Code values: 0 -> 0.0, 1 -> +threshold, 2 -> -threshold (reference
    gradient_compression.cc quantize_2bit semantics).
    """
    g = grad.astype(jnp.float32) + residual.astype(jnp.float32)
    q = jnp.where(g >= threshold, threshold,
                  jnp.where(g <= -threshold, -threshold, 0.0))
    new_residual = (g - q).astype(grad.dtype)
    codes = jnp.where(g >= threshold, 1, jnp.where(g <= -threshold, 2, 0))
    flat = codes.reshape(-1).astype(jnp.uint32)
    size = flat.shape[0]
    pad = (-size) % _CODES_PER_WORD
    if pad:
        flat = jnp.pad(flat, (0, pad))
    words = flat.reshape(-1, _CODES_PER_WORD)
    shifts = (2 * jnp.arange(_CODES_PER_WORD, dtype=jnp.uint32))[None, :]
    # disjoint bit positions: sum == bitwise-or, and jnp has no ufunc.reduce
    packed = jnp.sum(words << shifts, axis=1, dtype=jnp.uint32)
    return packed, new_residual


def dequantize_2bit(packed, shape, threshold=0.5, dtype=jnp.float32):
    """Inverse of :func:`quantize_2bit`."""
    size = int(np.prod(shape))
    shifts = (2 * jnp.arange(_CODES_PER_WORD, dtype=jnp.uint32))[None, :]
    codes = (packed[:, None] >> shifts) & jnp.uint32(3)
    flat = codes.reshape(-1)[:size]
    vals = jnp.where(flat == 1, threshold,
                     jnp.where(flat == 2, -threshold, 0.0))
    return vals.reshape(shape).astype(dtype)


class GradientCompression:
    """Per-key stateful compressor (parity: reference
    ``GradientCompression`` + python ``set_gradient_compression``
    kwargs). Types: ``"2bit"`` (threshold quantisation with per-key
    error-feedback residuals, 16x smaller wire) and ``"fp16"`` (a
    half-precision wire cast, 2x smaller, stateless — the cheap knob
    for DCN-spanning pushes where 2-bit's signal loss is unwanted).
    """

    def __init__(self, type="2bit", threshold=0.5):
        if type not in ("2bit", "fp16"):
            raise MXNetError("unsupported compression type %r" % (type,))
        try:
            threshold = float(threshold)  # reference params arrive as strings
        except (TypeError, ValueError):
            raise MXNetError("threshold must be a number, got %r"
                             % (threshold,))
        if not threshold > 0:
            raise MXNetError("threshold must be positive")
        self.type = type
        self.threshold = threshold
        self._residuals = {}

    def compress(self, key, grad):
        """Compress one gradient (jax array); 2bit tracks the residual
        under ``key`` (per device-shard keys: pass (name, shard_idx))."""
        if self.type == "fp16":
            return grad.astype(jnp.float16)
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros(grad.shape, grad.dtype)
        packed, res = quantize_2bit(grad, res, self.threshold)
        self._residuals[key] = res
        return packed

    def decompress(self, packed, shape, dtype=jnp.float32):
        if self.type == "fp16":
            return packed.astype(dtype).reshape(shape)
        return dequantize_2bit(packed, shape, self.threshold, dtype)


def compressed_psum(x, axis_name, compressor_state, threshold=0.5):
    """All-reduce with a 2-bit payload inside shard_map.

    ``compressor_state`` is the residual (same shape as x) carried by the
    caller across steps. Returns ``(summed, new_residual)``. The packed
    words (16x smaller than f32) are what travels over the mesh axis.
    """
    packed, new_res = quantize_2bit(x, compressor_state, threshold)
    gathered = jax.lax.all_gather(packed, axis_name, axis=0)  # (n, words)
    n = gathered.shape[0]
    deq = jax.vmap(lambda p: dequantize_2bit(p, x.shape, threshold,
                                             jnp.float32))(gathered)
    return jnp.sum(deq, axis=0).astype(x.dtype), new_res
