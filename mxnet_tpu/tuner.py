"""Runtime implementation tuning — the TPU-native ``operator_tune``.

Parity target: ``src/operator/operator_tune.{h,cc,-inl.h}``
(operator_tune.h:37-202). The reference micro-benchmarks every
elementwise kernel at startup to decide OMP-vs-serial per (op, size)
(``IsOMPFaster``, operator_tune.h:114), gated by
``MXNET_USE_OPERATOR_TUNING`` and dumped via
``MXNET_OUTPUT_TUNING_DATA``.

On TPU the intra-program half of that job belongs to XLA (it autotunes
kernel selection and tiling during compilation), so this module tunes
what the COMPILER cannot see: which of several lowerings the framework
should dispatch in the first place — a Pallas kernel vs a plain-XLA
composition, or a kernel meta-parameter like the flash-attention Q-block
size. Decisions are made the reference's way — measure each candidate
on the device the first time a (op, static-signature) pair is seen —
then cached in-process and optionally persisted across processes.

Env knobs (names follow the reference):
- ``MXNET_USE_OPERATOR_TUNING``  (default 1): 0 disables measurement;
  every choice falls back to the first (default) candidate.
- ``MXNET_OUTPUT_TUNING_DATA``   (default 0): log each measurement.
- ``MXNET_TUNING_CACHE``: path of a JSON file to load decisions from /
  save them to (the reference's startup-tuning analogue of a warm
  cache; first compile dominates candidate timing cost otherwise).
- ``MXNET_TUNING_REPEAT``        (default 3): timed runs per candidate.
"""
from __future__ import annotations

import json
import os
import time

from .base import get_env
from .log import get_logger

__all__ = ["OperatorTuner", "tuner", "tuned_choice"]

_log = get_logger("tuner")


def _is_tracer(x):
    import jax
    return isinstance(x, jax.core.Tracer)


class OperatorTuner:
    """Measure-and-cache chooser over named implementation candidates.

    ``choose(op, key, candidates)`` returns the label of the fastest
    candidate for this (op, key) signature. ``candidates`` is an ordered
    ``(label, thunk)`` sequence; each thunk runs its implementation once
    on synthetic data and returns a jax value (timed to completion with
    ``block_until_ready``). The first candidate is the default: it wins
    without measurement when tuning is disabled or measurement fails.
    """

    def __init__(self):
        self._cache = {}
        self._records = []          # (op, key, label, {label: seconds})
        self._loaded_path = None

    # -- persistence -------------------------------------------------------
    def _persist_path(self):
        return get_env("MXNET_TUNING_CACHE", "", str) or None

    def _load_persisted(self):
        path = self._persist_path()
        if path and path != self._loaded_path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._cache.update(json.load(f))
            except (OSError, ValueError) as e:
                _log.warning("tuner: could not load %s: %s", path, e)
            self._loaded_path = path

    def _save_persisted(self):
        path = self._persist_path()
        if not path:
            return
        try:
            with open(path, "w") as f:
                json.dump(self._cache, f, indent=0, sort_keys=True)
        except OSError as e:
            _log.warning("tuner: could not save %s: %s", path, e)

    # -- core --------------------------------------------------------------
    @staticmethod
    def enabled():
        return bool(get_env("MXNET_USE_OPERATOR_TUNING", 1, int))

    @staticmethod
    def _cache_key(op, key):
        return "%s|%s" % (op, key)

    def choose(self, op, key, candidates):
        """Pick a label from ``candidates`` for signature ``(op, key)``."""
        candidates = list(candidates)
        labels = [lab for lab, _ in candidates]
        if len(candidates) == 1:
            return labels[0]
        self._load_persisted()
        ck = self._cache_key(op, key)
        hit = self._cache.get(ck)
        if hit in labels:
            return hit
        if not self.enabled():
            return labels[0]
        best = self._measure(op, key, candidates)
        self._cache[ck] = best
        self._save_persisted()
        return best

    def cached(self, op, key, default):
        """Trace-time lookup: never measures (measurement runs real device
        work, which a traced context must not trigger)."""
        self._load_persisted()
        return self._cache.get(self._cache_key(op, key), default)

    def _measure(self, op, key, candidates):
        import jax
        repeat = max(1, get_env("MXNET_TUNING_REPEAT", 3, int))
        timings = {}
        for label, thunk in candidates:
            try:
                jax.block_until_ready(thunk())       # compile + warm
                best = float("inf")
                for _ in range(repeat):
                    t0 = time.perf_counter()
                    jax.block_until_ready(thunk())
                    best = min(best, time.perf_counter() - t0)
                timings[label] = best
            except Exception as e:                   # candidate invalid here
                _log.debug("tuner: %s[%s] candidate %r failed: %s",
                           op, key, label, e)
                timings[label] = float("inf")
        winner = min(timings, key=timings.get)
        if not (timings[winner] < float("inf")):
            winner = candidates[0][0]                # all failed: default
        self._records.append((op, key, winner, dict(timings)))
        if get_env("MXNET_OUTPUT_TUNING_DATA", 0, int):
            _log.info("tuner: %s[%s] -> %r  (%s)", op, key, winner,
                      ", ".join("%s=%.3gms" % (l, t * 1e3)
                                for l, t in timings.items()))
        return winner

    # -- introspection -----------------------------------------------------
    def records(self):
        """Measurement log: list of (op, key, winner, {label: seconds})."""
        return list(self._records)

    def clear(self):
        self._cache.clear()
        self._records.clear()
        self._loaded_path = None


_TUNER = OperatorTuner()


def tuner():
    return _TUNER


def tuned_choice(op, key, candidates, args=()):
    """Convenience dispatcher: measured choice when called eagerly, cached
    choice (falling back to the default candidate) when any of ``args``
    is a tracer — so ops using the tuner stay jit-safe."""
    candidates = list(candidates)
    if any(_is_tracer(a) for a in args):
        return _TUNER.cached(op, key, candidates[0][0])
    return _TUNER.choose(op, key, candidates)
