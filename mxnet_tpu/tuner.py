"""Runtime implementation tuning — the TPU-native ``operator_tune``.

Parity target: ``src/operator/operator_tune.{h,cc,-inl.h}``
(operator_tune.h:37-202). The reference micro-benchmarks every
elementwise kernel at startup to decide OMP-vs-serial per (op, size)
(``IsOMPFaster``, operator_tune.h:114), gated by
``MXNET_USE_OPERATOR_TUNING`` and dumped via
``MXNET_OUTPUT_TUNING_DATA``.

On TPU the intra-program half of that job belongs to XLA (it autotunes
kernel selection and tiling during compilation), so this module tunes
what the COMPILER cannot see: which of several lowerings the framework
should dispatch in the first place — a Pallas kernel vs a plain-XLA
composition, or a kernel meta-parameter like the flash-attention Q-block
size. Decisions are made the reference's way — measure each candidate
on the device the first time a (op, static-signature) pair is seen —
then cached in-process and optionally persisted across processes.

Env knobs (names follow the reference):
- ``MXNET_USE_OPERATOR_TUNING``  (default 1): 0 disables measurement;
  every choice falls back to the first (default) candidate.
- ``MXNET_OUTPUT_TUNING_DATA``   (default 0): log each measurement.
- ``MXNET_TUNING_CACHE``: path of a JSON file to load decisions from /
  save them to (the reference's startup-tuning analogue of a warm
  cache; first compile dominates candidate timing cost otherwise).
- ``MXNET_TUNING_REPEAT``        (default 3): timed runs per candidate.
"""
from __future__ import annotations

import json
import os
import time

from .base import get_env
from .log import get_logger

__all__ = ["OperatorTuner", "tuner", "tuned_choice", "plan_serving"]

_log = get_logger("tuner")


def _is_tracer(x):
    import jax
    return isinstance(x, jax.core.Tracer)


class OperatorTuner:
    """Measure-and-cache chooser over named implementation candidates.

    ``choose(op, key, candidates)`` returns the label of the fastest
    candidate for this (op, key) signature. ``candidates`` is an ordered
    ``(label, thunk)`` sequence; each thunk runs its implementation once
    on synthetic data and returns a jax value (timed to completion with
    ``block_until_ready``). The first candidate is the default: it wins
    without measurement when tuning is disabled or measurement fails.
    """

    def __init__(self):
        self._cache = {}
        self._records = []          # (op, key, label, {label: seconds})
        self._loaded_path = None

    # -- persistence -------------------------------------------------------
    def _persist_path(self):
        return get_env("MXNET_TUNING_CACHE", "", str) or None

    def _load_persisted(self):
        path = self._persist_path()
        if path and path != self._loaded_path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._cache.update(json.load(f))
            except (OSError, ValueError) as e:
                _log.warning("tuner: could not load %s: %s", path, e)
            self._loaded_path = path

    def _save_persisted(self):
        path = self._persist_path()
        if not path:
            return
        try:
            with open(path, "w") as f:
                json.dump(self._cache, f, indent=0, sort_keys=True)
        except OSError as e:
            _log.warning("tuner: could not save %s: %s", path, e)

    # -- core --------------------------------------------------------------
    @staticmethod
    def enabled():
        return bool(get_env("MXNET_USE_OPERATOR_TUNING", 1, int))

    @staticmethod
    def _cache_key(op, key):
        return "%s|%s" % (op, key)

    def choose(self, op, key, candidates):
        """Pick a label from ``candidates`` for signature ``(op, key)``."""
        candidates = list(candidates)
        labels = [lab for lab, _ in candidates]
        if len(candidates) == 1:
            return labels[0]
        self._load_persisted()
        ck = self._cache_key(op, key)
        hit = self._cache.get(ck)
        if hit in labels:
            return hit
        if not self.enabled():
            return labels[0]
        best = self._measure(op, key, candidates)
        self._cache[ck] = best
        self._save_persisted()
        return best

    def cached(self, op, key, default):
        """Trace-time lookup: never measures (measurement runs real device
        work, which a traced context must not trigger)."""
        self._load_persisted()
        return self._cache.get(self._cache_key(op, key), default)

    def _measure(self, op, key, candidates):
        import jax
        repeat = max(1, get_env("MXNET_TUNING_REPEAT", 3, int))
        timings = {}
        for label, thunk in candidates:
            try:
                jax.block_until_ready(thunk())       # compile + warm
                best = float("inf")
                for _ in range(repeat):
                    t0 = time.perf_counter()
                    jax.block_until_ready(thunk())
                    best = min(best, time.perf_counter() - t0)
                timings[label] = best
            except Exception as e:                   # candidate invalid here
                _log.debug("tuner: %s[%s] candidate %r failed: %s",
                           op, key, label, e)
                timings[label] = float("inf")
        winner = min(timings, key=timings.get)
        if not (timings[winner] < float("inf")):
            winner = candidates[0][0]                # all failed: default
        self._records.append((op, key, winner, dict(timings)))
        if get_env("MXNET_OUTPUT_TUNING_DATA", 0, int):
            _log.info("tuner: %s[%s] -> %r  (%s)", op, key, winner,
                      ", ".join("%s=%.3gms" % (l, t * 1e3)
                                for l, t in timings.items()))
        return winner

    # -- introspection -----------------------------------------------------
    def records(self):
        """Measurement log: list of (op, key, winner, {label: seconds})."""
        return list(self._records)

    def clear(self):
        self._cache.clear()
        self._records.clear()
        self._loaded_path = None


_TUNER = OperatorTuner()


def tuner():
    return _TUNER


# ---------------------------------------------------------------------------
# Card-corpus autotuner (serving plans)
# ---------------------------------------------------------------------------
# The OperatorTuner above measures CANDIDATE IMPLEMENTATIONS at first
# use; this half closes the other loop the reference never had: derive
# the SERVING CONFIGURATION (batch-bucket set, pipeline depth) from the
# persisted program-card corpus — measured per-bucket step-ms and the
# observed coalesced-row histogram across past runs
# (compile_cache.corpus_records) — instead of pow-2 defaults. The
# learned-cost-model framing is Kaufman et al. (arXiv:2008.01040): the
# corpus is the feature store, the interpolated cost model below its
# first, deliberately simple reader.

def _merge_rows_hist(records, max_batch):
    hist = {}
    for r in records:
        for k, v in (r.get("rows_hist") or {}).items():
            try:
                rows, n = int(k), int(v)
            except (TypeError, ValueError):
                continue
            if 1 <= rows <= max_batch and n > 0:
                hist[rows] = hist.get(rows, 0) + n
    return hist


def _merge_bucket_ms(records):
    """{bucket: mean dispatch->fetched ms} pooled over records."""
    acc = {}
    for r in records:
        for b, st in (r.get("bucket_ms") or {}).items():
            try:
                b = int(b)
                t = float(st.get("total_ms", 0.0))
                c = int(st.get("count", 0))
            except (TypeError, ValueError, AttributeError):
                continue
            if c > 0:
                e = acc.setdefault(b, [0.0, 0])
                e[0] += t
                e[1] += c
    return {b: t / c for b, (t, c) in acc.items() if c}


def _cost_model(mean_ms):
    """ms(batch) from measured per-bucket means: linear interpolation
    between measured points, proportional extrapolation outside them,
    and a plain ``ms = batch`` (pure linear) prior with NO measurements
    — so the planner still works on a rows-histogram-only corpus."""
    pts = sorted(mean_ms.items())

    def cost(b):
        if not pts:
            return float(b)
        if b <= pts[0][0]:
            return pts[0][1] * b / pts[0][0]
        if b >= pts[-1][0]:
            return pts[-1][1] * b / pts[-1][0]
        for (b0, m0), (b1, m1) in zip(pts, pts[1:]):
            if b0 <= b <= b1:
                f = (b - b0) / float(b1 - b0)
                return m0 + f * (m1 - m0)
        return float(b)
    return cost


def _pick_buckets(hist, max_batch, cost, max_buckets):
    """Optimal <=max_buckets bucket-top set over the observed row
    counts, minimising expected per-batch cost
    sum_r hist[r] * cost(smallest chosen bucket >= r) by exact DP over
    the candidate tops (every observed row count, plus max_batch which
    MUST be in the set so any request is coverable). Deterministic:
    ties break toward fewer buckets, then the lexicographically
    smaller set."""
    cands = sorted(set(list(hist) + [max_batch]))
    n = len(cands)
    weights = [hist.get(c, 0) for c in cands]
    # seg_cost[j][i]: rows in cands(j..i] served at bucket cands[i]
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def seg_cost(j, i):
        # candidates j+1..i (0-based, inclusive) map to bucket cands[i]
        return (prefix[i + 1] - prefix[j + 1]) * cost(cands[i])

    first_cost = [ (prefix[i + 1] - prefix[0]) * cost(cands[i])
                   for i in range(n)]
    INF = float("inf")
    # dp[k][i]: min cost covering cands[0..i] with k buckets, cands[i]
    # a bucket top; parent pointers reconstruct the set
    max_k = max(1, min(int(max_buckets), n))
    dp = [[INF] * n for _ in range(max_k + 1)]
    parent = [[None] * n for _ in range(max_k + 1)]
    for i in range(n):
        dp[1][i] = first_cost[i]
    for k in range(2, max_k + 1):
        for i in range(k - 1, n):
            best, arg = INF, None
            for j in range(k - 2, i):
                c = dp[k - 1][j] + seg_cost(j, i)
                if c < best:
                    best, arg = c, j
            dp[k][i] = best
            parent[k][i] = arg
    last = n - 1               # max_batch must top the set
    best_k, best_cost = 1, dp[1][last]
    for k in range(2, max_k + 1):
        # strict improvement required: ties prefer FEWER buckets
        if dp[k][last] < best_cost - 1e-12:
            best_k, best_cost = k, dp[k][last]
    tops, k, i = [], best_k, last
    while i is not None and k >= 1:
        tops.append(cands[i])
        i = parent[k][i]
        k -= 1
    return sorted(tops), best_cost


def _plan_inflight(records, default=2, cap=8):
    """Pipeline depth from the measured serve spans: while a batch's
    d2h fetch blocks a resolver, the coalescer can keep ~d2h/batch
    extra batches in flight; +1 for the one being built. Falls back to
    ``default`` without span data."""
    d2h, batch = [0.0, 0], [0.0, 0]
    for r in records:
        sp = r.get("spans") or {}
        for name, acc in (("serve_d2h", d2h), ("serve_batch", batch)):
            st = sp.get(name) or {}
            try:
                t, c = float(st.get("total_ms", 0.0)), int(
                    st.get("count", 0))
            except (TypeError, ValueError):
                continue
            if c > 0:
                acc[0] += t
                acc[1] += c
    if not d2h[1] or not batch[1]:
        return int(default)
    d2h_ms = d2h[0] / d2h[1]
    batch_ms = max(batch[0] / batch[1], 1e-6)
    import math
    return max(1, min(int(cap), 1 + int(math.ceil(d2h_ms / batch_ms))))


def _layout_key(layout):
    """Comparable identity of one partition layout: mesh axes + data
    axis + the RULE TREE, with the resolved ``sharded_params`` map
    stripped — that field is derived from whichever parameter shapes
    happened to be in hand when the summary was taken (an engine
    summarising at plan-load time has none yet; a corpus row banked at
    close has all of them), so keeping it would make identical layouts
    compare unequal. None (no partitioning) is its own identity."""
    if not isinstance(layout, dict):
        return None
    part = layout.get("partition")
    if isinstance(part, dict):
        part = {k: v for k, v in part.items() if k != "sharded_params"}
    key = dict(layout, partition=part)
    return json.dumps(key, sort_keys=True)


def plan_serving(records, max_batch=None, max_buckets=6,
                 default_inflight=2, graph=None, layout=None):
    """Deterministic serving plan from ``kind == "serving"`` corpus
    records: the bucket set minimising expected padded batch cost over
    the observed coalesced-row histogram (measured per-bucket step-ms
    as the cost model, linear prior without measurements) and a
    ``max_inflight`` derived from the measured d2h/batch span ratio.

    ``graph`` (an engine's ``graph_fingerprint()``) restricts planning
    to records stamped with the SAME graph — corpora are shared per
    cache dir, and another model's traffic must not shape this one's
    buckets. ``layout`` (an engine's ``partition_summary()``) rides
    into the returned plan AND restricts planning the same way: rows
    measured under an mp-sharded layout carry different per-bucket
    step costs than replicated rows of the same graph — the filter
    ALWAYS applies (a replicated engine, ``layout=None``, only plans
    from rows with no layout stamp), comparing via ``_layout_key`` so
    the derived ``sharded_params`` map never splits identical
    layouts.

    Returns a JSON-native dict (it round-trips through the JSONL
    corpus store unchanged) or None when the corpus holds no usable
    serving data. Same records -> same plan, always: the autotuner
    must be a pure function of the corpus.
    """
    recs = [r for r in (records or [])
            if isinstance(r, dict) and r.get("kind") == "serving"]
    if graph is not None:
        recs = [r for r in recs if r.get("graph") == graph]
    lkey = _layout_key(layout)
    recs = [r for r in recs if _layout_key(r.get("layout")) == lkey]
    if max_batch is None:
        max_batch = max((int(r.get("max_batch") or 0) for r in recs),
                        default=0)
    max_batch = int(max_batch or 0)
    if max_batch < 1:
        return None
    hist = _merge_rows_hist(recs, max_batch)
    if not hist:
        return None
    mean_ms = _merge_bucket_ms(recs)
    cost = _cost_model(mean_ms)
    buckets, expected = _pick_buckets(hist, max_batch, cost, max_buckets)
    total_batches = sum(hist.values())
    return {
        "kind": "autotune_plan",
        "version": 1,
        "graph": graph,
        "layout": layout,
        "max_batch": max_batch,
        "buckets": [int(b) for b in buckets],
        "max_inflight": _plan_inflight(recs, default=default_inflight),
        "expected_cost_ms_per_batch": round(expected / total_batches, 4)
        if total_batches else None,
        "basis": {
            "records": len(recs),
            "observed_batches": total_batches,
            "distinct_rows": len(hist),
            "measured_buckets": sorted(int(b) for b in mean_ms),
        },
    }


def tuned_choice(op, key, candidates, args=()):
    """Convenience dispatcher: measured choice when called eagerly, cached
    choice (falling back to the default candidate) when any of ``args``
    is a tracer — so ops using the tuner stay jit-safe."""
    candidates = list(candidates)
    if any(_is_tracer(a) for a in args):
        return _TUNER.cached(op, key, candidates[0][0])
    return _TUNER.choose(op, key, candidates)
