"""Network visualization.

Parity: reference ``python/mxnet/visualization.py`` — ``print_summary``
(per-layer param counts) and ``plot_network`` (graphviz; gated on the
graphviz package being present).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """(parity: visualization.print_summary)"""
    import json
    if positions is None:
        positions = [0.44, 0.64, 0.74, 1.0]
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    show_shape = shape is not None
    shape_dict = {}
    if show_shape:
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape_partial(**shape)
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = 0
    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null" and not name.endswith(("weight", "bias", "gamma",
                                               "beta")):
            cls_name = "%s (%s)" % (name, "input")
            out_shape = shape_dict.get(name + "_output",
                                       shape_dict.get(name, ""))
            print_row([cls_name, str(out_shape or ""), 0, ""], positions)
            continue
        if op == "null":
            continue
        out_name = name + "_output"
        out_shape = shape_dict.get(out_name, "")
        # param count: sum over this node's null inputs that look learnable
        params = 0
        for in_idx, *_ in node["inputs"]:
            in_node = nodes[in_idx]
            if in_node["op"] == "null" and in_node["name"].startswith(name) \
                    and in_node["name"].endswith(("weight", "bias",
                                                  "gamma", "beta",
                                                  "parameters")):
                s = shape_dict.get(in_node["name"], None)
                if s:
                    params += int(np.prod(s))
        total_params += params
        first_conn = ",".join(nodes[i]["name"]
                              for i, *_ in node["inputs"]
                              if nodes[i]["op"] != "null")
        print_row(["%s (%s)" % (name, op), str(out_shape or ""), params,
                   first_conn], positions)
    print("=" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """(parity: visualization.plot_network — requires graphviz)"""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires the graphviz package")
    import json
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    hidden = set()
    for i, node in enumerate(nodes):
        name = node["name"]
        if node["op"] == "null":
            if hide_weights and name.endswith(("weight", "bias", "gamma",
                                               "beta", "moving_mean",
                                               "moving_var")):
                hidden.add(i)
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label="%s\n%s" % (name, node["op"]),
                     shape="box")
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for in_idx, *_ in node["inputs"]:
            if in_idx in hidden:
                continue
            dot.edge(nodes[in_idx]["name"], node["name"])
    return dot
