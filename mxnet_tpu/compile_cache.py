"""Persisted AOT executable cache + the program-card corpus store.

No reference counterpart — the reference recompiled its graph executors
per process and called it cheap (CUDA kernels were prebuilt; only graph
planning ran at bind). On XLA the per-process cost is an actual
compiler invocation per program signature: serving warmup compiles one
program per batch bucket, a bench round compiles the train step before
it can measure anything, and BENCH_r03–r05 burned their entire on-chip
budget in exactly this startup window. This module is the zero-cold-
start tier ROADMAP item 3 calls for — the tune-once-serve-forever loop
of TVM (arXiv:1802.04799) native to our runtime:

* **executable store** — ``executor._InstrumentedProgram`` hands every
  freshly compiled executable to ``store()``, which serializes it (the
  PJRT executable serialization behind
  ``jax.experimental.serialize_executable``) into a content-addressed
  file keyed on sha256 of (StableHLO module text, abstract signature
  incl. shardings, donation set, backend platform, device topology,
  jax+jaxlib versions). The next process ``load()``s the key and
  deserializes INSTEAD of invoking XLA — restart, serving warmup and
  bench rounds skip the compiler entirely.

* **graceful degradation** — any mismatch (corrupt blob, stale
  jax/jaxlib version tag, different backend or mesh/device topology,
  deserialization failure) REJECTS the entry and falls back to a fresh
  compile, with one structured warning per (entry, cause) and a
  ``compile_cache.reject`` counter bump. A cache must never be able to
  break dispatch.

* **telemetry** — ``compile_cache.hit`` / ``.miss`` / ``.store`` /
  ``.reject`` counters plus ``.bytes_read`` / ``.bytes_written``, and
  the deserialize phase timed as a ``jit_deserialize`` span, so
  program cards and ``telemetry.snapshot()`` distinguish disk-hits
  from compiles (the warm-smoke lane gates on exactly this).

* **card corpus** — an append-only JSONL store persisting the program
  cards (FLOPs, bytes-accessed, compile ms) and measured serving data
  (rows histogram, per-bucket step ms) across runs:
  ``corpus_append()`` / ``corpus_records()``. The corpus is the raw
  material for the learned-cost-model line of work (Kaufman et al.
  arXiv:2008.01040); ``tuner.plan_serving`` reads it to pick serving
  bucket sets and ``max_inflight`` from measured data instead of
  pow-2 defaults.

Enablement: ``MXNET_COMPILE_CACHE=<dir>`` (empty/``0`` disables — the
default, so tests stay hermetic). The corpus lives at
``MXNET_CARD_CORPUS`` or ``<cache dir>/card_corpus.jsonl``.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import threading
import time

from . import telemetry
from . import faults
from .log import get_logger

__all__ = ["enabled", "cache_dir", "lowered_key", "quick_key",
           "index_get", "index_put", "load", "store",
           "corpus_path", "corpus_append", "corpus_records", "env_meta",
           "source_fingerprint"]

_log = get_logger("mxnet_tpu.compile_cache")

# one structured warning per (key, cause-kind): a poisoned entry that
# every bucket trips over must not log a storm
_WARNED = set()      # guarded by: _lock
_lock = threading.Lock()

_MAGIC = b"MXTPUCC1"
_FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# Enablement / environment identity
# ---------------------------------------------------------------------------

def cache_dir():
    """The cache directory (``MXNET_COMPILE_CACHE``), or None when the
    persisted tier is off (unset/empty/``0``)."""
    d = os.environ.get("MXNET_COMPILE_CACHE", "")
    if not d or d == "0":
        return None
    return d


_DIR_TRUST = {}      # guarded by: _lock


def _trusted_dir():
    """The cache dir, or None when it must not be trusted: entries are
    PICKLE payloads, so loading from a directory another user can
    write into is local arbitrary code execution. The dir must either
    not exist yet (we create it with default umask perms) or be owned
    by this uid and not group/world-writable. Distrust warns once and
    disables the persisted tier — never an error."""
    d = cache_dir()
    if d is None:
        return None
    with _lock:
        t = _DIR_TRUST.get(d)
    if t is None:
        try:
            st = os.stat(d)
            t = bool(st.st_uid == os.getuid()
                     and not (st.st_mode & 0o022))
        except FileNotFoundError:
            t = True            # created by us on first store
        except OSError:
            t = False
        if not t:
            _log.warning(
                "compile_cache: %s is not owned by this user or is "
                "group/world-writable — the persisted executable tier "
                "is DISABLED (a foreign-writable store could feed "
                "arbitrary pickles to deserialization)", d)
        # the stat/warn runs unlocked (filesystem I/O must not hold the
        # registry lock); a concurrent first-call races to the same
        # verdict and the write below is idempotent
        with _lock:
            _DIR_TRUST[d] = t
    return d if t else None


def enabled():
    """Whether executables persist to disk this process (requires a
    TRUSTED cache dir — see ``_trusted_dir``)."""
    return _trusted_dir() is not None and _serialize_api() is not None


def persistable(donated=()):
    """Whether a program with this donation set may use the persisted
    tier. Donated-buffer programs are EXCLUDED by default: executing a
    deserialized input-donating executable intermittently corrupts the
    process heap on jaxlib 0.4.36 (glibc ``corrupted double-linked
    list`` aborts at a later free — reproduced through Module.fit's
    fused train step; forward/serving programs are stable across
    hundreds of warm starts). ``MXNET_COMPILE_CACHE_DONATED=1`` opts
    donated programs back in on a jaxlib whose PJRT executable
    deserialization handles input-output aliasing release correctly."""
    if not donated:
        return True
    return os.environ.get("MXNET_COMPILE_CACHE_DONATED", "") == "1"


def _serialize_api():
    """The jax AOT-serialization module, or None on jaxlibs without it
    (the cache then degrades to disabled — never to an error)."""
    try:
        from jax.experimental import serialize_executable as se
        return se
    except Exception:
        return None


def env_meta():
    """The identity of THIS process's compile environment — everything
    a serialized executable is only valid under: jax/jaxlib versions,
    backend platform, and the local device topology (a cache written
    on an 8-device mesh must not load into a 1-device process)."""
    import jax
    import jaxlib
    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "devices": [[d.platform, int(d.id)] for d in devs],
    }


# ---------------------------------------------------------------------------
# Content-addressed key
# ---------------------------------------------------------------------------

def lowered_key(kind, lowered, signature=None, donated=()):
    """sha256 key for one lowered program: the StableHLO module text
    (the graph content), the named abstract signature incl. sharding
    strings (placement), the donation set, and the environment identity
    from ``env_meta()``. Returns None when the program cannot be keyed
    (exotic lowerings without a text form) — the caller then simply
    skips the persisted tier for that program."""
    try:
        text = lowered.as_text()
    except Exception:
        return None
    h = hashlib.sha256()
    h.update(_MAGIC)
    meta = env_meta()
    h.update(json.dumps(
        [kind, meta["jax"], meta["jaxlib"], meta["backend"],
         meta["devices"], list(donated or ()), signature],
        sort_keys=True).encode())
    h.update(text.encode())
    return h.hexdigest()


def entry_path(key):
    """On-disk path of one cache entry (two-level fan-out so a hot
    cache directory stays listable)."""
    d = cache_dir()
    if d is None:
        return None
    return os.path.join(d, key[:2], key + ".mxcc")


# ---------------------------------------------------------------------------
# Quick-key index: the trace-skip tier
# ---------------------------------------------------------------------------
# The content key above is bulletproof (it hashes the actual StableHLO)
# but computing it requires TRACING the program — a visible slice of a
# warm start (per-bucket jit_trace is ~15% of a cold serving warmup).
# The quick key is computable WITHOUT tracing, from everything that
# determines what the trace WOULD produce:
#   * the caller's graph fingerprint (``_GraphProgram`` hashes its
#     symbol JSON + the ambient layout default),
#   * a fingerprint of the package source tree ((relpath, size,
#     mtime_ns) of every .py file — editing any op implementation
#     invalidates every quick entry),
#   * every ``MXNET_*`` env knob except the cache's own (framework
#     flags like MXNET_FUSED_BN_ADD_RELU change trace-time lowering),
#   * the abstract signature incl. shardings, the donation set, and
#     ``env_meta()``.
# A quick-key hit resolves through a tiny index file to the content
# entry (which still verifies versions/backend/topology/checksum), so
# the worst a stale index can do is a rejected load -> fresh compile.

_SRC_FP = None       # guarded by: _lock

# cache/corpus/telemetry toggles do not change what a trace produces —
# including them would split the cache for no reason
_GRAPH_ENV_EXCLUDE = frozenset((
    "MXNET_COMPILE_CACHE", "MXNET_CARD_CORPUS", "MXNET_TELEMETRY"))


def source_fingerprint():
    """sha256 over this package's .py files as (relpath, size,
    mtime_ns) — any source edit (or a fresh checkout) invalidates the
    trace-skip tier, which then falls back to trace + content key."""
    global _SRC_FP
    with _lock:
        fp = _SRC_FP
    if fp is None:
        root = os.path.dirname(os.path.abspath(__file__))
        items = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                items.append([os.path.relpath(p, root), st.st_size,
                              st.st_mtime_ns])
        fp = hashlib.sha256(
            json.dumps(items, sort_keys=True).encode()).hexdigest()
        # the tree walk runs unlocked; first writer wins (both racers
        # hashed the same tree)
        with _lock:
            if _SRC_FP is None:
                _SRC_FP = fp
            fp = _SRC_FP
    return fp


def _graph_env():
    env = {k: v for k, v in os.environ.items()
           if k.startswith("MXNET_") and k not in _GRAPH_ENV_EXCLUDE}
    # MXTPU_IMAGE_LAYOUT seeds the layout default at import
    if "MXTPU_IMAGE_LAYOUT" in os.environ:
        env["MXTPU_IMAGE_LAYOUT"] = os.environ["MXTPU_IMAGE_LAYOUT"]
    return env


def quick_key(kind, graph_key, signature=None, donated=()):
    """Trace-free cache key (see the tier comment above). ``graph_key``
    is the caller's JSON-safe graph fingerprint; None disables the
    tier for that program."""
    if graph_key is None:
        return None
    h = hashlib.sha256()
    h.update(b"MXTPUQK1")
    try:
        h.update(json.dumps(
            [kind, graph_key, source_fingerprint(), _graph_env(),
             env_meta(), list(donated or ()), signature],
            sort_keys=True).encode())
    except (TypeError, ValueError):
        return None
    return h.hexdigest()


def _index_path(qkey):
    d = cache_dir()
    if d is None:
        return None
    return os.path.join(d, "index", qkey[:2], qkey + ".json")


def index_get(qkey):
    """Content key the quick key resolves to, or None. A mangled index
    file reads as a miss (the content entry's own verification is the
    real gate)."""
    if qkey is None:
        return None
    p = _index_path(qkey)
    if p is None or not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            rec = json.load(f)
        key = rec.get("key")
        return key if isinstance(key, str) else None
    except (OSError, ValueError):
        return None


def index_put(qkey, content_key):
    """Point the quick key at a stored content entry (atomic write;
    failures are warn-once no-ops like store())."""
    if qkey is None or content_key is None:
        return False
    p = _index_path(qkey)
    if p is None:
        return False
    tmp = None
    try:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p), suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump({"key": content_key, "created": time.time()}, f)
        os.replace(tmp, p)
        return True
    except OSError as e:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        _warn_once(qkey, "index_write", str(e))
        return False


# ---------------------------------------------------------------------------
# Entry file format: MAGIC + u32 meta-length + meta JSON + pickled blob
# ---------------------------------------------------------------------------

def _write_entry(path, meta, blob):
    """Atomic write (tmp + rename) of one cache entry."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    mj = json.dumps(meta, sort_keys=True).encode()
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(_MAGIC)
            f.write(len(mj).to_bytes(4, "little"))
            f.write(mj)
            f.write(blob)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(mj) + len(blob) + len(_MAGIC) + 4


def _read_entry(path):
    """(meta, blob) of one entry file; raises ValueError on a mangled
    container (bad magic / truncated header)."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:len(_MAGIC)] != _MAGIC:
        raise ValueError("bad magic")
    off = len(_MAGIC)
    mlen = int.from_bytes(raw[off:off + 4], "little")
    off += 4
    meta = json.loads(raw[off:off + mlen].decode())
    blob = raw[off + mlen:]
    return meta, blob


def _warn_once(key, cause, detail):
    """ONE structured warning per (key, cause) through log.py — the
    single-warning contract the poisoning tests pin."""
    with _lock:
        if (key, cause) in _WARNED:
            return
        _WARNED.add((key, cause))
    _log.warning(
        "compile_cache: rejected entry %s cause=%s (%s) — falling back "
        "to a fresh compile; delete the entry (or the cache dir) to "
        "stop paying the load attempt", key[:12], cause, detail)


def _reject(key, cause, detail):
    telemetry.counter_inc("compile_cache.reject")
    telemetry.counter_inc("compile_cache.reject.%s" % cause)
    _warn_once(key, cause, detail)
    return None


# ---------------------------------------------------------------------------
# Load / store
# ---------------------------------------------------------------------------

def load(key, kind=None):
    """Deserialize the executable stored under ``key``, or None.

    Every mismatch degrades to None (the caller compiles fresh):
    missing entry (``compile_cache.miss``), corrupt container or blob,
    stale jax/jaxlib version tag, different backend platform or
    device/mesh topology, or a deserialization error — each rejected
    with a single structured warning and a ``compile_cache.reject``
    counter bump. The deserialize phase records as a
    ``jit_deserialize`` telemetry span, the disk-tier counterpart of
    ``jit_compile``."""
    se = _serialize_api()
    path = entry_path(key)
    if se is None or path is None or _trusted_dir() is None:
        return None
    # chaos site: an injected raise behaves exactly like a mangled
    # entry — the reject path fires and the caller compiles fresh (a
    # cache must never be able to break dispatch, injected or not)
    try:
        faults.fire("compile_cache.load")
    except faults.InjectedFault as e:
        return _reject(key, "injected", str(e))
    if not os.path.exists(path):
        telemetry.counter_inc("compile_cache.miss")
        return None
    try:
        meta, blob = _read_entry(path)
    except (OSError, ValueError, UnicodeDecodeError) as e:
        return _reject(key, "corrupt", "unreadable entry: %s" % e)
    env = env_meta()
    for field in ("jax", "jaxlib"):
        if meta.get(field) != env[field]:
            return _reject(
                key, "version",
                "%s %s in entry vs %s running" % (field, meta.get(field),
                                                  env[field]))
    if meta.get("backend") != env["backend"]:
        return _reject(key, "backend", "entry compiled for backend %r, "
                       "process runs %r" % (meta.get("backend"),
                                            env["backend"]))
    if meta.get("devices") != env["devices"]:
        return _reject(
            key, "mesh",
            "entry compiled for device topology %s, process has %s"
            % (meta.get("devices"), env["devices"]))
    if meta.get("blob_sha256") != hashlib.sha256(blob).hexdigest():
        return _reject(key, "corrupt", "blob checksum mismatch")
    try:
        with telemetry.span("jit_deserialize"):
            payload, in_tree, out_tree = pickle.loads(blob)
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:
        return _reject(key, "deserialize",
                       "%s: %s" % (type(e).__name__, e))
    telemetry.counter_inc("compile_cache.hit")
    telemetry.counter_inc("compile_cache.bytes_read", len(blob))
    return compiled


def store(key, compiled, kind=None, entry=None, signature=None):
    """Serialize one freshly compiled executable under ``key``. All
    failures (backends without executable serialization, unpicklable
    trees, full disk) degrade to a warning-once no-op — persisting is
    an optimisation, never a requirement. Returns the stored byte
    count (0 when skipped)."""
    se = _serialize_api()
    path = entry_path(key)
    if se is None or path is None:
        return 0
    try:
        payload, in_tree, out_tree = se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
    except Exception as e:
        _warn_once(key, "serialize", "%s: %s" % (type(e).__name__, e))
        telemetry.counter_inc("compile_cache.store_fail")
        return 0
    meta = dict(env_meta())
    meta.update({
        "format": _FORMAT_VERSION,
        "kind": kind,
        "entry": entry,
        "signature": signature,
        "created": time.time(),
        "blob_sha256": hashlib.sha256(blob).hexdigest(),
        "blob_bytes": len(blob),
    })
    try:
        n = _write_entry(path, meta, blob)
    except OSError as e:
        _warn_once(key, "write", str(e))
        telemetry.counter_inc("compile_cache.store_fail")
        return 0
    telemetry.counter_inc("compile_cache.store")
    telemetry.counter_inc("compile_cache.bytes_written", n)
    return n


# ---------------------------------------------------------------------------
# Program-card corpus (append-only JSONL across runs)
# ---------------------------------------------------------------------------

def corpus_path():
    """The JSONL corpus file: ``MXNET_CARD_CORPUS`` if set (``0``/empty
    disables), else ``<cache dir>/card_corpus.jsonl``, else None."""
    p = os.environ.get("MXNET_CARD_CORPUS", "")
    if p == "0":
        return None
    if p:
        return p
    d = cache_dir()
    return os.path.join(d, "card_corpus.jsonl") if d else None


def corpus_append(record, path=None):
    """Append one JSON record (a dict; a ``kind`` field keys readers)
    to the corpus. Returns True when written. Never raises — the
    corpus is telemetry, not state."""
    path = path or corpus_path()
    if path is None or not isinstance(record, dict):
        return False
    try:
        line = json.dumps(record, sort_keys=True)
    except (TypeError, ValueError) as e:
        _log.warning("compile_cache: corpus record not JSON-safe: %s", e)
        return False
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with _lock:
            with open(path, "a") as f:
                f.write(line + "\n")
    except OSError as e:
        _log.warning("compile_cache: corpus append to %s failed: %s",
                     path, e)
        return False
    telemetry.counter_inc("compile_cache.corpus_append")
    return True


def corpus_records(path=None, kind=None):
    """All parseable corpus records, oldest first (``kind`` filters on
    the record's ``kind`` field). Unparseable lines — a run killed
    mid-append — are skipped, not fatal."""
    path = path or corpus_path()
    if path is None or not os.path.exists(path):
        return []
    out = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        _log.warning("compile_cache: corpus read from %s failed: %s",
                     path, e)
        return []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and (kind is None
                                      or rec.get("kind") == kind):
            out.append(rec)
    return out


def programs_record(extra=None):
    """One corpus record snapshotting ``telemetry.programs()`` plus the
    fit/serve span stats — what a run banks so the NEXT run's autotuner
    has measured step-ms next to each card's FLOPs/bytes."""
    snap_spans = telemetry.span_stats()
    rec = {
        "kind": "programs",
        "ts": time.time(),
        "env": env_meta(),
        "cards": telemetry.programs(),
        "spans": {k: v for k, v in snap_spans.items()
                  if k in telemetry.FIT_PHASE_SPANS
                  or k in telemetry.SERVE_SPANS
                  or k in telemetry.COMPILE_SPANS},
    }
    if extra:
        rec.update(extra)
    return rec
