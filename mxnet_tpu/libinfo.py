"""Information about the native runtime libraries.

Parity: reference ``python/mxnet/libinfo.py`` (find_lib_path locating
libmxnet.so). Here the native pieces are the host-runtime libraries
built by the top-level Makefile into ``mxnet_tpu/_lib`` (the compute
path is JAX/XLA and ships no .so of its own).
"""
from __future__ import annotations

import os

__all__ = ["find_lib_path", "find_include_path", "__version__"]


def find_lib_path():
    """Find the paths to the native runtime libraries.

    Returns
    -------
    lib_path : list(string)
        List of all found library paths. May be empty when the native
        libraries are not built — every consumer has a Python fallback.
    """
    lib_dir = os.path.join(os.path.dirname(os.path.abspath(
        os.path.expanduser(__file__))), "_lib")
    names = ["libmxtpu_io.so", "libmxtpu_engine.so"]
    return [os.path.join(lib_dir, n) for n in names
            if os.path.exists(os.path.join(lib_dir, n))]


def find_include_path():
    """Path to the native sources (headers are in-source, src/*.cc)."""
    curr = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    return os.path.join(os.path.dirname(curr), "src")


__version__ = "0.12.1"
