"""Optimizers.

Parity: reference ``python/mxnet/optimizer.py`` (registry, lr/wd mult
handling, num_update bookkeeping, Updater) with the update math delegated
to the fused update ops (ops/optimizer_ops.py ≙ reference
``src/operator/optimizer_op.cc``) so each parameter update compiles to a
single fused XLA kernel.
"""
from __future__ import annotations

import math
import pickle

import numpy as np

try:  # bf16 is the TPU-native low precision; fp16 kept for API parity
    import ml_dtypes as _ml
    _LOW_PRECISION = (np.dtype(np.float16), np.dtype(_ml.bfloat16))
except ImportError:  # pragma: no cover
    _LOW_PRECISION = (np.dtype(np.float16),)

from .base import MXNetError, registry_create
from .ndarray import ndarray as _nd
from .ndarray import (sgd_update, sgd_mom_update, mp_sgd_update,
                      mp_sgd_mom_update, adam_update, rmsprop_update,
                      rmspropalex_update, ftrl_update, zeros)  # noqa: F401 (zeros: API re-export)

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "DCASGD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Test", "Updater", "get_updater",
           "create", "register"]


def _state_zeros(weight, dtype=None):
    """Optimizer state shaped AND placed like the weight: under the
    mesh-DP Module weights are committed replicated over the device mesh,
    and states must share that placement or the fused update ops would
    mix single-device and mesh-committed operands."""
    import jax
    import jax.numpy as jnp
    raw = jnp.zeros(weight.shape, dtype or weight._data.dtype)
    sh = _nd._multi_device_sharding(weight._data)
    raw = jax.device_put(raw, sh) if sh is not None \
        else _nd._to_device(raw, weight.context)
    return _nd._wrap(raw, weight.context)

register, _alias, _create, _get = registry_create("optimizer")


class Optimizer:
    """Base optimizer (parity: optimizer.Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = dict(param_idx2name)
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None \
            else ({}, [])
        self.param_dict = param_dict or {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    @property
    def learning_rate(self):
        """(parity: optimizer.learning_rate)"""
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        """(parity: optimizer.set_learning_rate)"""
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already "
                              "been defined.")
        self.lr = lr

    def set_lr_scale(self, args_lrscale):
        """Deprecated alias of set_lr_mult (parity:
        optimizer.set_lr_scale)."""
        self.set_lr_mult({self.idx2name.get(i, i): s
                          for i, s in args_lrscale.items()})

    # -- registry ----------------------------------------------------------
    @staticmethod
    def create_optimizer(name, **kwargs):
        return _create(name, **kwargs)

    @staticmethod
    def register(cls):
        return register(cls)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in _LOW_PRECISION:
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    # -- lr / wd -----------------------------------------------------------
    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        attr, arg_names = self.sym_info
        for name in arg_names:
            if name in attr and "__lr_mult__" in attr[name]:
                self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # reference rule: no weight decay on 1-D params
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        attr, arg_names = self.sym_info
        for name in arg_names:
            if name in attr and "__wd_mult__" in attr[name]:
                self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common_kwargs(self):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    # -- sparse (lazy) update plumbing --------------------------------------
    def _sparse_rows(self, grad):
        """(rows, rows_grad) for a row_sparse gradient, with rescale/clip
        applied (reference optimizer_op.cc row_sparse kernels)."""
        import jax.numpy as jnp
        g = grad._rsp_data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return grad._rsp_indices.astype(jnp.int32), g

    @staticmethod
    def _is_row_sparse(grad):
        from .ndarray.sparse import RowSparseNDArray
        return isinstance(grad, RowSparseNDArray)


@register
class SGD(Optimizer):
    """SGD with momentum and optional fp16 master-weight multi-precision
    (parity: optimizer.SGD backed by reference fused sgd ops)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _state_zeros(weight)

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in _LOW_PRECISION:
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self._is_row_sparse(grad):
            self._update_sparse(weight, grad, state, lr, wd)
            return
        kw = self._common_kwargs()
        if isinstance(state, tuple):  # multi-precision
            mom, w32 = state
            if mom is not None:
                mp_sgd_mom_update(weight, grad, mom, w32, lr=lr, wd=wd,
                                  momentum=self.momentum, **kw)
            else:
                mp_sgd_update(weight, grad, w32, lr=lr, wd=wd, **kw)
        elif state is not None:
            sgd_mom_update(weight, grad, state, lr=lr, wd=wd,
                           momentum=self.momentum, **kw)
        else:
            sgd_update(weight, grad, lr=lr, wd=wd, **kw)

    update_multi_precision = update

    def _update_sparse(self, weight, grad, state, lr, wd):
        """Lazy SGD: only rows present in the gradient are touched
        (reference optimizer_op.cc SGDUpdateRspRspImpl — momentum decay
        is also lazy, matching the reference's row_sparse-state kernel).
        Multi-precision state (mom, w32) updates the fp32 master rows and
        casts back (reference MP_SGD row_sparse kernels)."""
        rows, g = self._sparse_rows(grad)
        master = weight
        if isinstance(state, tuple):                    # multi-precision
            state, master = state
        w = master._data
        wr = w.take(rows, axis=0)
        g = g.astype(w.dtype) + wd * wr
        if state is not None:
            mom = state._data
            m_new = self.momentum * mom.take(rows, axis=0) - lr * g
            state._set_data(mom.at[rows].set(m_new))
            w_new = w.at[rows].add(m_new)
        else:
            w_new = w.at[rows].add(-lr * g)
        master._set_data(w_new)
        if master is not weight:
            weight._set_data(w_new.astype(weight._data.dtype))


@register
class NAG(SGD):
    """Nesterov accelerated SGD (parity: optimizer.NAG:592-622 —
    wd folds into the applied gradient BEFORE the momentum update)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad], {"a_min": -self.clip_gradient,
                                                "a_max": self.clip_gradient})
        if state is not None:
            state *= self.momentum
            grad = grad + wd * weight
            state += grad
            grad = grad + self.momentum * state
            weight -= lr * grad
        else:
            weight -= lr * (grad + wd * weight)

    def update_multi_precision(self, index, weight, grad, state):
        # SGD's class-level alias would bind SGD.update (wrong rule);
        # NAG's own rule runs on the fp32 master, then casts back
        if not isinstance(state, tuple):
            return self.update(index, weight, grad, state)
        mom, w32 = state
        self.update(index, w32, grad.astype("float32"), mom)
        weight._set_data(w32._data.astype(weight._data.dtype))


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity: optimizer.SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad], {"a_min": -self.clip_gradient,
                                                "a_max": self.clip_gradient})
        from .ndarray import random as _rnd
        noise = _rnd.normal(0, math.sqrt(lr), shape=weight.shape)
        weight -= lr / 2 * (grad + wd * weight)
        weight += noise


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: optimizer.DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_state_zeros(weight),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad], {"a_min": -self.clip_gradient,
                                                "a_max": self.clip_gradient})
        mom, previous_weight = state
        if mom is not None:
            mom *= self.momentum
            mom += -lr * (grad + wd * weight + self.lamda * grad * grad *
                          (weight - previous_weight))
        else:
            mom = -lr * (grad + wd * weight + self.lamda * grad * grad *
                         (weight - previous_weight))
        previous_weight._set_data(weight._data)
        weight += mom


@register
class Adam(Optimizer):
    """(parity: optimizer.Adam; fused adam_update op)"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_state_zeros(weight),
                _state_zeros(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr = lr * math.sqrt(coef2) / coef1
        mean, var = state
        if self._is_row_sparse(grad):
            # lazy Adam (reference AdamUpdateRspRspImpl): only gradient
            # rows advance their moments
            rows, g = self._sparse_rows(grad)
            w, m, v = weight._data, mean._data, var._data
            wr = w.take(rows, axis=0)
            g = g + wd * wr
            m_new = self.beta1 * m.take(rows, axis=0) + (1 - self.beta1) * g
            v_new = self.beta2 * v.take(rows, axis=0) + \
                (1 - self.beta2) * g * g
            import jax.numpy as jnp
            step = lr * m_new / (jnp.sqrt(v_new) + self.epsilon)
            mean._set_data(m.at[rows].set(m_new))
            var._set_data(v.at[rows].set(v_new))
            weight._set_data(w.at[rows].add(-step))
            return
        adam_update(weight, grad, mean, var, lr=lr, wd=wd, beta1=self.beta1,
                    beta2=self.beta2, epsilon=self.epsilon,
                    **self._common_kwargs())


@register
class AdaGrad(Optimizer):
    """(parity: optimizer.AdaGrad)"""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _state_zeros(weight, dtype=np.float32)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self._is_row_sparse(grad):
            # lazy AdaGrad (reference AdagradUpdateRspRspImpl)
            import jax.numpy as jnp
            rows, g = self._sparse_rows(grad)
            w, h = weight._data, state._data
            wr = w.take(rows, axis=0)
            h_new = h.take(rows, axis=0) + g * g
            state._set_data(h.at[rows].set(h_new))
            step = lr * (g / jnp.sqrt(h_new + self.float_stable_eps)
                         + wd * wr)
            weight._set_data(w.at[rows].add(-step))
            return
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad], {"a_min": -self.clip_gradient,
                                                "a_max": self.clip_gradient})
        history = state
        history += grad * grad
        weight -= lr * (grad / (history + self.float_stable_eps).sqrt()
                        + wd * weight)


@register
class RMSProp(Optimizer):
    """(parity: optimizer.RMSProp; centered=True uses Graves variant)"""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_state_zeros(weight, dtype=np.float32),
                    _state_zeros(weight, dtype=np.float32),
                    _state_zeros(weight, dtype=np.float32))
        return (_state_zeros(weight, dtype=np.float32),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs()
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if not self.centered:
            (n,) = state
            rmsprop_update(weight, grad, n, lr=lr, wd=wd, gamma1=self.gamma1,
                           epsilon=self.epsilon, **kw)
        else:
            n, g, delta = state
            rmspropalex_update(weight, grad, n, g, delta, lr=lr, wd=wd,
                               gamma1=self.gamma1, gamma2=self.gamma2,
                               epsilon=self.epsilon, **kw)


@register
class AdaDelta(Optimizer):
    """(parity: optimizer.AdaDelta)"""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_state_zeros(weight, dtype=np.float32),
                _state_zeros(weight, dtype=np.float32))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad], {"a_min": -self.clip_gradient,
                                                "a_max": self.clip_gradient})
        acc_g, acc_delta = state
        acc_g *= self.rho
        acc_g += (1.0 - self.rho) * grad * grad
        current_delta = ((acc_delta + self.epsilon).sqrt()
                         / (acc_g + self.epsilon).sqrt()) * grad
        acc_delta *= self.rho
        acc_delta += (1.0 - self.rho) * current_delta * current_delta
        weight -= current_delta + wd * weight


@register
class Ftrl(Optimizer):
    """(parity: optimizer.Ftrl; fused ftrl_update op)"""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_state_zeros(weight, dtype=np.float32),
                _state_zeros(weight, dtype=np.float32))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        ftrl_update(weight, grad, z, n, lr=lr, wd=wd, lamda1=self.lamda1,
                    beta=self.beta, **self._common_kwargs())


@register
class Test(Optimizer):
    """(parity: optimizer.Test — used by unit tests)"""

    def create_state(self, index, weight):
        return _state_zeros(weight, dtype=np.float32)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._set_data(weight._data)


@register
class ccSGD(SGD):
    """Deprecated alias of SGD (parity: optimizer.ccSGD — same update)."""


@register
class Adamax(Optimizer):
    """AdaMax, the infinity-norm Adam variant (parity: optimizer.Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (_state_zeros(weight, dtype=np.float32),
                _state_zeros(weight, dtype=np.float32))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad],
                               {"a_min": -self.clip_gradient,
                                "a_max": self.clip_gradient})
        m, u = state
        m *= self.beta1
        m += (1.0 - self.beta1) * grad
        u._set_data(jnp_maximum(self.beta2 * u._data,
                                jnp_abs(grad._data)))
        weight -= lr * m / u


@register
class Nadam(Optimizer):
    """Nesterov-accelerated Adam (parity: optimizer.Nadam — Dozat's
    momentum-schedule formulation)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (_state_zeros(weight, dtype=np.float32),
                _state_zeros(weight, dtype=np.float32))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = _nd._invoke("clip", [grad],
                               {"a_min": -self.clip_gradient,
                                "a_max": self.clip_gradient})
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t *
                                                        self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** (
            (t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1

        m, v = state
        m *= self.beta1
        m += (1.0 - self.beta1) * grad
        v *= self.beta2
        v += (1.0 - self.beta2) * grad * grad

        grad_prime = grad / (1.0 - self.m_schedule)
        m_prime = m / (1.0 - m_schedule_next)
        v_prime = v / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * m_prime
        weight -= lr * m_bar / (_nd._invoke("sqrt", [v_prime], {}) +
                                self.epsilon)


def jnp_maximum(a, b):
    import jax.numpy as jnp
    return jnp.maximum(a, b)


def jnp_abs(a):
    import jax.numpy as jnp
    return jnp.abs(a)


create = Optimizer.create_optimizer


class Updater:
    """Applies an optimizer to (index, grad, weight) calls — the object a
    KVStore runs server-side (parity: optimizer.get_updater/Updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        elif not self.states_synced.get(index, True):
            self._sync_state(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def _sync_state(self, index, weight):
        """Host states from set_states -> NDArrays on the weight's context
        (parity: optimizer.Updater.sync_state_context). A weight that is
        committed to a device mesh pulls the state onto the weight's
        RULE-DERIVED placement — under the partition engine a parameter
        may be mp-SHARDED, not just dp-replicated, and loaded checkpoint
        states must re-enter on that same layout or the donated SPMD
        step / fused batch update would mix placements (the old code
        assumed the replicated dp layout and force-applied the weight's
        sharding to every leaf — a shape-mismatched leaf, e.g. a scalar
        schedule state, would be rejected by an mp sharding's rank).
        A leaf the weight's shape rides the weight's exact sharding;
        any other shape replicates onto the same mesh."""
        import jax
        sh = _nd._multi_device_sharding(weight._data)
        repl = None
        if sh is not None:
            mesh = getattr(sh, "mesh", None)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                repl = NamedSharding(mesh, PartitionSpec())
        wshape = tuple(weight._data.shape)

        def _conv(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(_conv(x) for x in s)
            if isinstance(s, _nd.NDArray):
                out = s.as_in_context(weight.context)
            else:
                out = _nd.array(np.asarray(s), ctx=weight.context,
                                dtype=np.asarray(s).dtype)
            if sh is not None:
                target = sh if tuple(out._data.shape) == wshape \
                    else (repl or sh)
                out._set_data(jax.device_put(out._data, target))
            return out
        self.states[index] = _conv(self.states[index])
        self.states_synced[index] = True

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = {k: False for k in self.states}

    def update_batch(self, indices, grads, weights):
        """Per-index loop; FusedUpdater overrides with one fused dispatch."""
        for i, g, w in zip(indices, grads, weights):
            self(i, g, w)

    def get_states(self, dump_optimizer=False):
        def _np(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(_np(x) for x in s)
            return s.asnumpy() if hasattr(s, "asnumpy") else s
        states = {k: _np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer
                            else states)


def _make_batch_update(kname, statics, mp, inner_n):
    """Pure whole-parameter-set update: ``(ws, states, gs, lrs, wds, ts)
    -> (new_ws, new_states)`` applying one optimizer kernel to every
    parameter. Shared by ``FusedUpdater.update_batch`` (jitted alone, one
    dispatch per optimizer step) and the Module whole-train-step program
    (``executor._GraphProgram.train_step_fn``), so both paths run
    IDENTICAL update arithmetic. ``mp[i]`` marks multi-precision entries
    whose state tuple ends with the fp32 master weight; ``inner_n[i]`` is
    the kernel-owned state count."""
    from .parallel.opt_kernels import get_kernel
    _, update_fn = get_kernel(kname)
    n = len(mp)

    def step(ws, states, gs, lrs, wds, ts):
        new_ws, new_states = [], []
        for i in range(n):
            w, s, g = ws[i], states[i], gs[i]
            h = dict(statics)
            h["lr"], h["wd"] = lrs[i], wds[i]
            if mp[i]:
                p = s[-1]                       # fp32 master
                inner = s[:-1]
                p_new, inner_new = update_fn(
                    p, g.astype(p.dtype), inner, ts[i], h)
                new_ws.append(p_new.astype(w.dtype))
                ns = tuple(x.astype(o.dtype) for x, o in
                           zip(inner_new[:inner_n[i]], inner)) + (p_new,)
            else:
                w_new, s_new = update_fn(w, g, s, ts[i], h)
                new_ws.append(w_new.astype(w.dtype))
                ns = tuple(x.astype(o.dtype) for x, o in
                           zip(s_new[:inner_n[i]], s))
            new_states.append(ns)
        return new_ws, new_states

    return step


class FusedUpdater(Updater):
    """Updater with a batched one-dispatch path: ``update_batch`` traces
    EVERY parameter's update rule into a single jitted XLA program
    (weight/state buffers donated), so an optimizer step costs one device
    dispatch instead of one per parameter — the decisive cost on a
    remoted PJRT backend. The update math is the same pure kernels the
    SPMD trainer uses (parallel/opt_kernels.py ≙ reference
    optimizer_op.cc:39-299); state layout and pickled get_states format
    stay identical to ``Updater``. Per-(index) ``__call__`` remains the
    fallback for sparse gradients and optimizers without a pure kernel.

    Under the dp-mesh Module the weights (and therefore the states —
    ``_state_zeros`` copies the weight's placement) are committed
    REPLICATED over the mesh: the donated buffers are the replicated
    copies, so both this phase-split batch step and the whole-step SPMD
    program (``executor.train_step_fn``) update every replica in place
    without a broadcast.
    """

    def __init__(self, optimizer):
        super().__init__(optimizer)
        self._jit_cache = {}
        self._mp_flags = {}

    def set_states(self, states):
        super().set_states(states)
        # states (and possibly the optimizer) were replaced wholesale;
        # multi-precision classification must be recomputed against them
        self._mp_flags.clear()

    # -- helpers -----------------------------------------------------------
    def _ensure_state(self, index, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
            self._mp_flags[index] = bool(
                self.optimizer.multi_precision
                and weight.dtype in _LOW_PRECISION)
        else:
            if not self.states_synced.get(index, True):
                self._sync_state(index, weight)
            if index not in self._mp_flags:
                # states loaded via set_states: the flag is a pure
                # function of optimizer config + weight dtype
                self._mp_flags[index] = bool(
                    self.optimizer.multi_precision
                    and weight.dtype in _LOW_PRECISION)
        return self.states[index]

    @staticmethod
    def _pack_state(state):
        """Eager state -> flat tuple of NDArrays, or None if the layout
        isn't expressible for the kernels (e.g. centered RMSProp)."""
        if state is None:
            return ()
        if isinstance(state, _nd.NDArray):
            return (state,)
        if isinstance(state, tuple):
            if all(isinstance(x, _nd.NDArray) for x in state):
                return tuple(state)
        return None

    def _gather_batch(self, kname, indices, weights):
        """(packed, mp, inner_n) state layout for a whole-parameter-set
        kernel step, creating/syncing states as needed — or (None, None,
        None) when any entry's layout can't ride the kernel program (the
        caller keeps the WHOLE batch on one path so update counts stay
        uniform)."""
        packed, mp, inner_n = [], [], []
        for i, w in zip(indices, weights):
            st = self._ensure_state(i, w)
            is_mp = self._mp_flags[i]
            if is_mp:
                inner, w32 = st
                tup = self._pack_state(inner)
                tup = tup + (w32,) if tup is not None else None
            else:
                tup = self._pack_state(st)
            if tup is None or (kname == "nag" and len(tup) == (1 if is_mp
                                                               else 0)):
                # inexpressible state layout (or momentum-less NAG, whose
                # kernel always reads s[0])
                return None, None, None
            packed.append(tup)
            mp.append(is_mp)
            inner_n.append(len(tup) - (1 if is_mp else 0))
        return packed, mp, inner_n

    def update_batch(self, indices, grads, weights):
        """One fused optimizer step over parallel lists of (index, grad,
        weight). Falls back to the per-index path when any element can't
        ride the kernel program."""
        from .parallel import opt_kernels as _ok
        from .ndarray import sparse as _sp
        opt = self.optimizer

        def _fallback():
            for i, g, w in zip(indices, grads, weights):
                self(i, g, w)

        try:
            kname, hyper = _ok.hyper_from_optimizer(opt)
        except MXNetError:
            return _fallback()
        if getattr(opt, "centered", False) or \
                any(isinstance(g, _sp.BaseSparseNDArray) for g in grads):
            return _fallback()

        packed, mp, inner_n = self._gather_batch(kname, indices, weights)
        if packed is None:
            return _fallback()

        # host-side bookkeeping exactly as the eager path does it:
        # update counts first, then scheduler-aware lr/wd per index.
        # Shipped as THREE (n,) arrays, not 3n scalar pytree leaves —
        # every leaf is its own host->device transfer per step on a
        # remoted PJRT backend (~50ms/step at ResNet-50 param counts)
        for i in indices:
            opt._update_count(i)
        ts = np.asarray([opt._index_update_count[i] for i in indices],
                        np.float32)
        lrs = np.asarray([opt._get_lr(i) for i in indices], np.float32)
        wds = np.asarray([opt._get_wd(i) for i in indices], np.float32)

        statics = tuple(sorted(
            (k, v) for k, v in hyper.items() if k not in ("lr", "wd")))
        # dtype objects are hashable — stringifying them cost ~6ms/step
        # of pure host overhead at ResNet-50 param counts
        key = (kname, statics,
               tuple((w._data.shape, w._data.dtype, m, n)
                     for w, m, n in zip(weights, mp, inner_n)),
               tuple(tuple((x._data.shape, x._data.dtype)
                           for x in tup) for tup in packed))
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = self._build_step(kname, dict(statics), list(mp),
                                  list(inner_n))
            self._jit_cache[key] = fn

        raw_ws = [w._data for w in weights]
        raw_gs = [g._data for g in grads]
        raw_states = [tuple(x._data for x in tup) for tup in packed]
        from .executor import record_dispatch
        record_dispatch("opt_update")
        # donated positions (0, 1) are INFERRED: fn comes from
        # _build_step, whose returned program declares donate_argnums —
        # mxflow's returns-donating summary tracks it through the
        # cache-or-build binding, no manual marker needed
        new_ws, new_states = fn(raw_ws, raw_states, raw_gs, lrs, wds, ts)

        for w, tup, nw, ntup in zip(weights, packed, new_ws, new_states):
            w._set_data(nw)
            for x, nx in zip(tup, ntup):
                x._set_data(nx)

    def _build_step(self, kname, statics, mp, inner_n):
        import jax
        return jax.jit(_make_batch_update(kname, statics, mp, inner_n),
                       donate_argnums=(0, 1))


def get_updater(optimizer):
    return FusedUpdater(optimizer)
