"""mxnet_tpu — a TPU-native deep-learning framework with the capabilities
of Apache MXNet 0.12.1.

Brand-new design (not a port): JAX/XLA is the compute substrate, PJRT the
async engine, pjit/shard_map over device meshes the distributed backend.
See SURVEY.md for the reference's structure this framework mirrors at the
API level, and the per-module docstrings for the TPU-first design of each
subsystem.

Typical use matches the reference::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
import os as _os

if _os.environ.get("MXNET_TPU_FORCE_CPU", "") in ("1", "true"):
    # debugging/CI escape hatch (the reference's MXNET_ENGINE_TYPE=
    # NaiveEngine analogue): force the host platform before any backend
    # init, overriding site-level accelerator selection
    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")

# multi-process SPMD wiring, set by tools/launch.py (parity:
# KVStore::InitPSEnv reading DMLC_PS_ROOT_URI etc., kvstore.h:254).
# Must run before any backend touch, hence at import. A no-op without
# MXNET_TPU_COORDINATOR; connection errors propagate — a worker that
# cannot reach the coordinator must die loudly, not train as a
# 1-process job. See mxnet_tpu/dist.py for the elastic posture.
from . import dist
dist.init_from_env()

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus
from . import layout
from . import config
from . import ops
from . import imperative
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import random
from .random import seed

# re-export sampler conveniences onto mx.random (parity: mx.random.uniform)
random.uniform = nd.random.uniform
random.normal = nd.random.normal

from . import symbol                 # noqa: E402
from . import symbol as sym          # noqa: E402
from .symbol import Symbol           # noqa: E402
from .executor import Executor       # noqa: E402
from . import initializer            # noqa: E402
from .initializer import init_registry  # noqa: E402
from . import optimizer              # noqa: E402
from . import lr_scheduler           # noqa: E402
from . import metric                 # noqa: E402
from . import io                     # noqa: E402
from . import recordio               # noqa: E402
from . import kvstore                # noqa: E402
from . import kvstore as kv          # noqa: E402  (reference: mx.kv)
from .kvstore import KVStore         # noqa: E402
from . import gradient_compression  # noqa: E402
from . import predictor              # noqa: E402
from . import serving                # noqa: E402
from . import decode                 # noqa: E402
from . import callback               # noqa: E402
from . import model                  # noqa: E402
from . import module                 # noqa: E402
from . import module as mod          # noqa: E402
from . import gluon                  # noqa: E402
from . import parallel               # noqa: E402

__version__ = "0.1.0"
from . import operator               # noqa: E402
from . import rnn                    # noqa: E402
from . import telemetry              # noqa: E402
from . import faults                 # noqa: E402
from . import checkpoint             # noqa: E402
from .checkpoint import CheckpointManager  # noqa: E402
from . import flight                 # noqa: E402

# flight recorder env knobs (MXNET_FLIGHT_DIR / MXNET_METRICS_INTERVAL_MS
# / MXNET_METRICS_PORT) take effect at import; all three default off
flight._maybe_autostart()
from . import compile_cache          # noqa: E402
from . import profiler               # noqa: E402
from . import tuner                  # noqa: E402
from . import monitor                # noqa: E402
from .monitor import Monitor         # noqa: E402
from . import visualization          # noqa: E402
from . import visualization as viz   # noqa: E402
from . import test_utils             # noqa: E402
from . import image                  # noqa: E402
from . import image as img           # noqa: E402
from . import engine                 # noqa: E402
from . import storage                # noqa: E402
from . import resource               # noqa: E402
from . import name                   # noqa: E402
from .attribute import AttrScope     # noqa: E402
from . import attribute              # noqa: E402
from . import registry               # noqa: E402
from . import log                    # noqa: E402
from . import libinfo                # noqa: E402
from . import rtc                    # noqa: E402
from . import contrib                # noqa: E402
from . import executor_manager       # noqa: E402
from . import kvstore_server         # noqa: E402
from . import torch                  # noqa: E402
from . import torch as th            # noqa: E402
from . import initializer as init    # noqa: E402
from . import monitor as mon         # noqa: E402
from . import random as rnd          # noqa: E402
