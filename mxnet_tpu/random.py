"""Global PRNG state.

Parity: reference ``python/mxnet/random.py`` (mx.random.seed) backed by
per-device PRNG Resources. TPU-native design: a single splittable JAX key;
eager ops split it (stateful convenience, like the reference), while
jitted graphs receive an explicit key argument from the executor so the
compiled computation stays pure (see ops/common.rng_scope).
"""
from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
_key = jax.random.key(0)


def seed(seed_state):
    """Seed the global generator (parity: mx.random.seed)."""
    global _key
    with _lock:
        _key = jax.random.key(int(seed_state))


def take_key():
    """Split off a fresh key (eager-mode random ops)."""
    global _key
    with _lock:
        _key, sub = jax.random.split(_key)
    return sub


def get_state():
    """JSON-safe snapshot of the global generator (the raw key data as
    a list of ints) — what a checkpoint persists so a resumed run
    replays the SAME key sequence the interrupted run would have."""
    import numpy as np
    with _lock:
        return np.asarray(jax.random.key_data(_key)).tolist()


def set_state(state):
    """Restore a :func:`get_state` snapshot (checkpoint resume)."""
    global _key
    import numpy as np
    data = np.asarray(state, dtype=np.uint32)
    with _lock:
        _key = jax.random.wrap_key_data(data)


# re-exported sampling helpers (mx.random.uniform etc.) are installed by
# mxnet_tpu/__init__.py from the generated nd namespace.
