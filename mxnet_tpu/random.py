"""Global PRNG state.

Parity: reference ``python/mxnet/random.py`` (mx.random.seed) backed by
per-device PRNG Resources. TPU-native design: a single splittable JAX key;
eager ops split it (stateful convenience, like the reference), while
jitted graphs receive an explicit key argument from the executor so the
compiled computation stays pure (see ops/common.rng_scope).
"""
from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
_key = jax.random.key(0)


def seed(seed_state):
    """Seed the global generator (parity: mx.random.seed)."""
    global _key
    with _lock:
        _key = jax.random.key(int(seed_state))


def take_key():
    """Split off a fresh key (eager-mode random ops)."""
    global _key
    with _lock:
        _key, sub = jax.random.split(_key)
    return sub


# re-exported sampling helpers (mx.random.uniform etc.) are installed by
# mxnet_tpu/__init__.py from the generated nd namespace.
