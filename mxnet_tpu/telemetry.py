"""Unified runtime telemetry: counters, host-span tracing, dispatch taps.

No reference counterpart — the reference's only runtime window was the
engine profiler's device spans (src/engine/profiler.cc). On a remoted
PJRT backend the HOST side (feed, shard_put, dispatch, fallback
decisions, blocking syncs) is where throughput goes to die — it is what
hid the 14x ``Module.fit`` gap until round 5 (PERF.md) — so this module
is the standing instrument every perf PR reads from:

* a **counter registry** — jitted-program dispatches by kind, jit-cache
  compiles vs. hits per ``_GraphProgram`` entry point, fused-step
  fallback events keyed by their stable ``FusedFallback.code``,
  host->device transfer bytes, blocking host syncs, kvstore traffic;
* **host-side span tracing** — ``with telemetry.span("feed"): ...``
  records wall-time intervals into a bounded ring buffer with a
  per-name duration histogram and a p50/p95/p99 ``snapshot()`` API;
* a **multi-subscriber dispatch registry** — ``on_dispatch(cb)`` /
  ``remove_dispatch(cb)`` replaces the old single-slot
  ``executor.dispatch_hook`` global (which probe, tests and telemetry
  silently clobbered off each other; the legacy name still works as a
  back-compat shim read by ``executor.record_dispatch``);
* **chrome-trace export** — ``chrome_events()`` renders the span ring
  as chrome://tracing ``X`` events; ``profiler.py`` merges them into
  the XLA device dump so host and device timelines land in ONE
  perfetto-loadable JSON;
* a **program-card registry** — every XLA program the executor
  compiles deposits a card (``record_program``) carrying its abstract
  input signature, trace/compile wall-time, ``cost_analysis`` FLOPs/
  bytes and ``memory_analysis`` footprint; ``program_dispatch`` bumps
  the card's dispatch count per launch, and ``snapshot()`` derives an
  ONLINE sustained-FLOP/s (and MFU, once ``set_peak_flops`` is told
  the chip's ceiling) from card FLOPs x dispatches / step-span time —
  the live counterpart of PERF.md's offline roofline table. Cards are
  plain JSON-safe dicts built by executor.py (this module stays
  stdlib-only and never imports jax);
* a **live device-buffer ledger** — ``ledger_track(obj, ...)`` charges
  a buffer to its context until ``obj`` is garbage-collected
  (weakref.finalize), maintaining per-context alive-bytes/alive-count/
  peak-bytes; ``ledger_top()`` lists the largest live buffers, which
  is what the executor stitches into enriched OOM errors;
* **causal ids** (the flight-recorder substrate, ISSUE 10) —
  ``with telemetry.causal(req_id=7): ...`` stamps every span recorded
  on the thread (or a span built with an explicit ``ctx=``, for spans
  that cross threads) with the ids of the request or fit step it
  serves. ``serving.submit()`` stamps a ``req_id`` that rides the
  request through coalesce → batch dispatch → d2h → resolve (batch
  spans carry the member ``req_ids``), ``Module.fit`` stamps
  ``(epoch, nbatch)`` onto feed/step/opt spans, and
  ``chrome_events()`` renders the shared ids as chrome-trace FLOW
  events (``ph: s/t/f``) so perfetto draws arrows linking one
  request's or step's spans across threads;
* an **event ring** — ``record_event(kind, **data)`` appends one
  discrete runtime event (a fault firing, a shed, a breaker trip, a
  checkpoint save) into a bounded ring; together with
  ``recent_spans()`` it is the last-N "what happened, when, to which
  request" record a crash postmortem (``mxnet_tpu/flight.py``) dumps.

Everything here is stdlib-only (no jax import) and cheap when disabled:
``MXNET_TELEMETRY=0`` (or ``disable()``) reduces every span to two
attribute reads and every counter to one branch. Counters and spans are
process-global — the fit loop, the kvstore and the io pipeline all feed
one registry, which is exactly what makes the merged trace readable.
"""
from __future__ import annotations

import collections
import itertools
import os
import socket
import threading
import time
import weakref

__all__ = [
    "enabled", "enable", "disable", "reset",
    "counter_inc", "counters", "snapshot", "span", "record_span",
    "span_stats", "span_count", "span_durations", "span_seconds",
    "causal", "current_causal", "record_event", "events",
    "recent_spans", "serving_queue_depth", "process_identity",
    "on_dispatch", "remove_dispatch", "dispatch_event",
    "record_jit", "record_fallback", "record_fault", "record_transfer",
    "record_host_sync", "chrome_events", "mark_trace_start",
    "record_program", "program_dispatch", "programs", "card_update",
    "card_annotate",
    "set_peak_flops", "ledger_track", "ledger", "ledger_top",
    "SPAN_RING_SIZE", "EVENT_RING_SIZE", "FIT_PHASE_SPANS",
    "SERVE_SPANS", "DECODE_SPANS", "COMPILE_SPANS",
    "MAX_PROGRAM_CARDS", "COUNTERS",
]

# ring capacities: bound memory for arbitrarily long training runs. The
# span ring keeps the most recent intervals for chrome export; duration
# histograms keep more samples per name so percentiles stay meaningful
# after the ring has wrapped.
SPAN_RING_SIZE = 4096
_DURATIONS_PER_NAME = 4096

# event ring: the flight recorder's last-N discrete-event record
# (faults, sheds, breaker trips, checkpoint saves, preemptions) — what
# a crash postmortem dumps next to the span ring
EVENT_RING_SIZE = 2048

# the fit-loop phase span names — the ONE list the bench/probe artifact
# summaries filter on, kept next to the code that records them so the
# BENCH/MULTICHIP accountings can't silently diverge
FIT_PHASE_SPANS = ("fit_batch", "feed", "step", "shard_put",
                   "metric_update", "metric_fetch", "opt_update",
                   "io_next", "callbacks", "epoch_sync",
                   "kv_push", "kv_pull")

# the serving-path span names (mxnet_tpu/serving.py): request time in
# queue, program dispatch per coalesced batch, the blocking d2h fetch,
# and the whole submit->resolve request latency whose p50/p95/p99 the
# serving artifacts and TelemetryLogger report
SERVE_SPANS = ("serve_wait", "serve_batch", "serve_d2h", "serve_request")

# the decode-tier span names (mxnet_tpu/decode.py): one slot's prefill
# dispatch, one batched decode step advancing every active slot a
# token (its duration IS the per-token latency the decode artifacts
# report), and the retire-time host assembly that resolves a finished
# sequence. A decode request's flow chains serve_wait -> serve_prefill
# -> serve_decode_step x N -> serve_detokenize -> serve_request.
DECODE_SPANS = ("serve_prefill", "serve_decode_step", "serve_detokenize")

# the program-build span names (executor._InstrumentedProgram /
# compile_cache): tracing, an actual XLA compile, and a disk-cache
# deserialize. The warm-start lanes gate on the compile-vs-deserialize
# split — a warm process serving every bucket must record ZERO
# jit_compile spans and >= one jit_deserialize per program
COMPILE_SPANS = ("jit_trace", "jit_compile", "jit_deserialize")

# program-card registry bound: recompile storms must not grow the
# registry without limit — the oldest card is dropped (its FLOPs x
# dispatches folded into the online total so MFU stays right)
MAX_PROGRAM_CARDS = 256

# the DECLARED counter-name registry: every ``counter_inc`` literal in
# the runtime must match one of these patterns (mxlint's
# registry-consistency pass cross-checks both directions — an
# undeclared name at the call site is a typo that never aggregates, a
# declared-but-never-bumped pattern is a dead dashboard row). A
# trailing ``.*`` covers a dynamic tail: fallback codes, fault sites,
# reject causes, shed causes, dispatch/program kinds.
COUNTERS = (
    "flight.postmortem", "flight.postmortem_fail",
    "dispatch.*", "jit.*", "recompile.*",
    "fused_fallback.*",
    "partition.replicated_fallback",
    "faults.injected", "faults.injected.*",
    "transfer.*", "host_sync.*",
    "kvstore.push", "kvstore.pull", "kvstore.wire_bytes",
    "kvstore.dist.collectives", "kvstore.dist.wire_bytes",
    "kvstore.dist.wire_bytes_raw", "kvstore.dist.fused_steps",
    "elastic.dead_workers", "elastic.remesh", "elastic.resumed",
    "exec_group.forward",
    "training.preempted",
    "divergence.detected", "divergence.skipped", "divergence.rollback",
    "checkpoint.save", "checkpoint.resume",
    "compile_cache.hit", "compile_cache.miss",
    "compile_cache.store", "compile_cache.store_fail",
    "compile_cache.reject", "compile_cache.reject.*",
    "compile_cache.bytes_read", "compile_cache.bytes_written",
    "compile_cache.corpus_append",
    "serving.requests", "serving.rows", "serving.batches",
    "serving.batch_rows", "serving.pad_rows", "serving.pad_bytes",
    "serving.resolved", "serving.failed_requests",
    "serving.shed_requests", "serving.shed_rows", "serving.shed.*",
    "serving.deadline_exceeded", "serving.retries",
    "serving.dispatch_failures", "serving.breaker_trips",
    "serving.breaker_fastfail",
    "decode.requests", "decode.tokens", "decode.steps",
    "decode.slot_admit", "decode.slot_retire",
    "decode.shed", "decode.shed.*", "decode.deadline_exceeded",
    "decode.prefill_compiles", "decode.resolved",
    "decode.failed_requests", "decode.dispatch_failures",
    "decode.retries", "decode.breaker_trips", "decode.breaker_fastfail",
    # fleet observability (ISSUE 18): per-channel gate-wait attribution
    # and the structured straggler verdicts the gate emits
    "heartbeat.gate_wait_ms.*", "heartbeat.gate_crossings.*",
    "dist.straggler",
)


class _State:
    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = os.environ.get("MXNET_TELEMETRY", "1") not in (
            "0", "false")


_state = _State()
_lock = threading.Lock()
_counters = {}           # guarded by: _lock
# span ring: (name, start_ns, end_ns, thread_id, causal_ctx_or_None)
# in perf_counter_ns time. Appends are deliberately LOCK-FREE
# (GIL-atomic deque ops on the per-batch hot path); see the
# _record_span disables.
_spans = collections.deque(maxlen=SPAN_RING_SIZE)   # guarded by: _lock
# event ring: (perf_ns, kind, data_dict_or_None, thread_id). Appends
# are lock-free for the same hot-path reason (some events fire under
# OTHER locks — the serving admission path records sheds while holding
# the engine lock, and stacking _lock under it per event buys nothing).
_events = collections.deque(maxlen=EVENT_RING_SIZE)  # guarded by: _lock
# per-thread causal ids (req_id / epoch+nbatch) stamped onto spans
# recorded while a causal() scope is active on that thread
_tls = threading.local()
_durations = {}          # name -> deque of durations  # guarded by: _lock
_span_total = {}         # name -> cumulative count    # guarded by: _lock
_span_seconds = {}       # guarded by: _lock
                         # name -> cumulative span seconds (uncapped) —
                         # the online-MFU denominator must cover EVERY
                         # step, not just the histogram ring's tail
_dispatch_subs = []      # guarded by: _lock
_gen = 0                 # guarded by: _lock
                         # bumped by reset(): spans straddling a reset
                         # belong to the OLD window and must not leak
                         # into the freshly cleared registry

# program cards: card["id"] -> card dict (insertion-ordered). The card
# OBJECT is shared with the executor wrapper that built it — dispatch
# bumps mutate it in place, and a reset() simply drops the registry
# reference; the wrapper re-installs (with a fresh dispatch count) on
# the next launch, so a windowed reset reads clean.
_programs = {}                  # guarded by: _lock
_programs_dropped_flops = 0.0   # guarded by: _lock
_peak_flops = None              # guarded by: _lock

# live device-buffer ledger: per-context alive/peak counters plus the
# individual live-buffer map that backs ledger_top() / OOM enrichment
_ledger = {}        # guarded by: _lock
                    # ctx key -> {alive_bytes, alive_count, peak_bytes,
                    #             tracked_total, tracked_bytes_total}
_ledger_live = {}   # guarded by: _lock
                    # token -> (ctx_key, nbytes, shape, dtype, kind,
                    #           keyed_key_or_None)
_ledger_keyed = {}  # guarded by: _lock
                    # (id(obj), ctx_key, kind) -> token, for
                    # replace=True re-tracking (a re-committed
                    # parameter replaces its prior charge instead of
                    # double-counting)
_ledger_seq = itertools.count(1)
# released tokens land here LOCK-FREE and are drained under _lock by
# the next ledger operation. The finalize callback must NOT take
# _lock: cyclic-GC (autograd tapes make NDArray cycles) can run the
# finalizer synchronously on a thread that already HOLDS _lock (any
# allocation inside a locked section can trip the GC threshold), and
# the non-reentrant lock would deadlock the process mid-training.
_ledger_pending = collections.deque()   # guarded by: _lock

# perf_counter<->epoch anchor, taken once at import: spans are stamped
# in the monotonic perf_counter timebase (immune to clock steps); the
# chrome exporter maps them back to epoch microseconds through this
# anchor so they can align with the device trace
_ANCHOR_PERF_NS = time.perf_counter_ns()
_ANCHOR_EPOCH_NS = time.time_ns()

# perf_counter_ns stamp of the last profiler trace start (chrome export
# filters to spans inside the trace window)
_trace_start_ns = None


# ---------------------------------------------------------------------------
# Enable/disable
# ---------------------------------------------------------------------------

def enabled():
    """Whether spans and counters record (default on; MXNET_TELEMETRY=0
    starts disabled). Dispatch SUBSCRIBERS fire regardless — they were
    installed explicitly."""
    return _state.enabled


def enable():
    _state.enabled = True   # mxlint: disable=thread-race -- GIL-atomic bool flip, read lock-free by every hot-path probe by design (PR 3's enabled() gate); a lock here would serialise every counter/span fast path


def disable():
    _state.enabled = False   # mxlint: disable=thread-race -- same GIL-atomic flag flip as enable()


def reset():
    """Clear every counter, span, histogram and program card
    (subscribers stay). Spans currently OPEN on any thread are dropped
    at their exit — a pre-reset interval must not appear in the new
    accounting window. The buffer LEDGER's live map survives (the
    buffers are still alive and their finalizers will still fire);
    its cumulative totals zero and peak rebases to the current alive
    level, so a windowed reader sees this window's high-water mark."""
    global _gen, _programs_dropped_flops
    with _lock:
        _gen += 1
        _counters.clear()
        _spans.clear()
        _events.clear()
        _durations.clear()
        _span_total.clear()
        _span_seconds.clear()
        _programs.clear()
        _programs_dropped_flops = 0.0
        _ledger_drain_locked()
        for st in _ledger.values():
            st["peak_bytes"] = st["alive_bytes"]
            st["tracked_total"] = 0
            st["tracked_bytes_total"] = 0


# ---------------------------------------------------------------------------
# Counter registry
# ---------------------------------------------------------------------------

def counter_inc(name, n=1):
    """Add ``n`` to counter ``name`` (no-op while disabled)."""
    if not _state.enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def counters():
    """Snapshot copy of the counter registry."""
    with _lock:
        return dict(_counters)


def record_jit(kind, hit):
    """One ``_GraphProgram``/updater jit-cache lookup: ``hit=False`` is
    a program build (trace + XLA compile on first execution), ``hit=True``
    a cached-program reuse. Fed by executor.py — the compile-vs-hit ratio
    is the recompile-storm detector."""
    if not _state.enabled:
        return
    what = "hit" if hit else "compile"
    with _lock:
        _counters["jit.%s" % what] = _counters.get("jit.%s" % what, 0) + 1
        k = "jit.%s.%s" % (what, kind)
        _counters[k] = _counters.get(k, 0) + 1


def serving_queue_depth(counts, prefix="serving."):
    """Admitted-but-unterminated serving requests, from a counter
    mapping: requests − resolved − post-admission sheds − failed.
    Admission sheds never entered ``requests`` (they must not drive
    the depth negative); coalesce/resolve/breaker sheds and failed
    requests DID, and each terminated its future. THE one copy of the
    formula — ``InferenceEngine.stats()`` (over its engine-local stats,
    ``prefix=""``), ``TelemetryLogger.log_serving`` and the flight
    recorder's sampler all call this, so a new terminal cause is a
    one-place change."""
    def g(key):
        return counts.get(prefix + key, 0)
    return (g("requests") - g("resolved")
            - (g("shed_requests") - g("shed.admission"))
            - g("failed_requests"))


def record_fallback(code):
    """One fused-step fallback event, keyed by the stable
    ``FusedFallback.code`` (module/base_module.FUSED_FALLBACK_CODES)."""
    counter_inc("fused_fallback.%s" % code)


def record_fault(site):
    """One INJECTED fault fired at a named ``faults.py`` site — counted
    as ``faults.injected.<site>`` (total under ``faults.injected``) so
    the chaos lane's artifact carries exact fire counts next to the
    shed/retry/resume counters the injections caused."""
    counter_inc("faults.injected")
    counter_inc("faults.injected.%s" % site)


def record_transfer(nbytes, direction="h2d"):
    """Host<->device transfer accounting (bytes + event count)."""
    if not _state.enabled:
        return
    with _lock:
        _counters["transfer.%s_bytes" % direction] = \
            _counters.get("transfer.%s_bytes" % direction, 0) + int(nbytes)
        _counters["transfer.%s_count" % direction] = \
            _counters.get("transfer.%s_count" % direction, 0) + 1


def record_host_sync(what="host"):
    """One BLOCKING host synchronisation (asnumpy/wait_to_read/metric
    flush) — the async-pipeline stalls PERF.md hunts for."""
    if not _state.enabled:
        return
    with _lock:
        _counters["host_sync.blocking"] = \
            _counters.get("host_sync.blocking", 0) + 1
        k = "host_sync.%s" % what
        _counters[k] = _counters.get(k, 0) + 1


# ---------------------------------------------------------------------------
# Dispatch registry (multi-subscriber; replaces the single-slot hook)
# ---------------------------------------------------------------------------

def on_dispatch(cb):
    """Subscribe ``cb(kind)`` to every jitted-program dispatch
    (``executor.record_dispatch``). Unlike the legacy single-slot
    ``executor.dispatch_hook`` global, any number of subscribers coexist
    — the probe, tests and telemetry no longer clobber each other.
    Returns ``cb`` for symmetric ``remove_dispatch(cb)``."""
    with _lock:
        if cb not in _dispatch_subs:
            _dispatch_subs.append(cb)
    return cb


def remove_dispatch(cb):
    """Unsubscribe a callback; unknown callbacks are ignored."""
    with _lock:
        try:
            _dispatch_subs.remove(cb)
        except ValueError:
            pass


def dispatch_event(kind):
    """Fan one dispatch out to the counter registry and every
    subscriber. Called by ``executor.record_dispatch`` — the ONE
    dispatch-reporting entry point (tools/run_checks.sh lints that no
    other site grows a raw hook call)."""
    if _state.enabled:
        with _lock:
            k = "dispatch.%s" % kind
            _counters[k] = _counters.get(k, 0) + 1
    # deliberately lock-free: list() is one GIL-atomic snapshot, and
    # subscriber callbacks must NOT run under _lock (a callback that
    # reads counters() would deadlock)
    if _dispatch_subs:   # mxlint: disable=lock-discipline -- GIL-atomic emptiness probe of an append/remove-only list
        for cb in list(_dispatch_subs):   # mxlint: disable=lock-discipline -- GIL-atomic snapshot copy; callbacks must run outside the lock
            cb(kind)


def dispatch_counts():
    """{kind: count} view of the dispatch counters (the probe's
    per-batch dispatch accounting reads this instead of installing its
    own hook)."""
    with _lock:
        return {k[len("dispatch."):]: v for k, v in _counters.items()
                if k.startswith("dispatch.")}


# ---------------------------------------------------------------------------
# Causal ids + discrete-event ring (the flight-recorder substrate)
# ---------------------------------------------------------------------------

class _Causal:
    """Scope installing causal ids (req_id / epoch+nbatch) as the
    thread's ambient span context; nests (inner ids shadow, the outer
    dict is restored on exit)."""
    __slots__ = ("_ids", "_prev")

    def __init__(self, ids):
        self._ids = ids

    def __enter__(self):
        self._prev = getattr(_tls, "ids", None)
        _tls.ids = self._ids
        return self

    def __exit__(self, *exc):
        _tls.ids = self._prev
        return False


def causal(**ids):
    """``with telemetry.causal(epoch=2, nbatch=17): ...`` — every span
    recorded on THIS thread inside the scope carries the given ids
    (``chrome_events()`` renders shared ids as flow arrows; postmortems
    and ``tools/flight_view.py`` group the ring by them). Spans that
    cross threads pass ``span(name, ctx=...)`` explicitly instead."""
    return _Causal(ids)


def current_causal():
    """The ambient causal-id dict of this thread (None outside any
    ``causal()`` scope)."""
    return getattr(_tls, "ids", None)


def record_event(kind, **data):
    """Append one discrete runtime event (a fault firing, a shed, a
    breaker trip, a checkpoint save) to the bounded event ring — the
    flight record a crash postmortem dumps. Lock-free (GIL-atomic
    bounded-deque append): events fire from hot paths and from inside
    OTHER locks (the serving admission path holds the engine lock).
    No-op while disabled."""
    if not _state.enabled:
        return
    _events.append((time.perf_counter_ns(), kind, data or None,   # mxlint: disable=lock-discipline -- GIL-atomic bounded-deque append; events fire under foreign locks
                    threading.get_ident()))


def events(n=None):
    """The retained event ring as JSON-safe dicts (oldest first):
    ``{"ts": epoch_s, "kind": ..., "tid": ..., "data": {...}|None}``.
    ``n`` keeps only the newest n."""
    with _lock:
        evs = list(_events)
    if n is not None:
        evs = evs[-int(n):]
    return [{"ts": round(_epoch_us(p_ns) / 1e6, 6), "kind": kind,
             "tid": tid, "data": data}
            for p_ns, kind, data, tid in evs]


def recent_spans(n=None):
    """The retained span ring as JSON-safe dicts (oldest first):
    ``{"name", "ts" (epoch_s), "dur_ms", "tid", "ctx"}`` — the causal
    ``ctx`` carries the req_id / step ids stamped by ``causal()`` or an
    explicit ``span(ctx=)``. ``n`` keeps only the newest n."""
    with _lock:
        spans = list(_spans)
    if n is not None:
        spans = spans[-int(n):]
    return [{"name": name, "ts": round(_epoch_us(s_ns) / 1e6, 6),
             "dur_ms": round((e_ns - s_ns) / 1e6, 4), "tid": tid,
             "ctx": None if ctx is None else dict(ctx)}
            for name, s_ns, e_ns, tid, ctx in spans]


# ---------------------------------------------------------------------------
# Host-side span tracing
# ---------------------------------------------------------------------------

class _Span:
    """Reentrant-per-instance-free timing scope; ~two perf_counter_ns
    calls + two deque appends when enabled, two attribute reads when
    disabled. ``ctx`` pins explicit causal ids (for spans that are
    entered on one thread and exited on another, e.g. the serving
    request spans); without it the recording thread's ambient
    ``causal()`` ids are captured at ENTER."""
    __slots__ = ("name", "_t0", "_gen", "_ctx")

    def __init__(self, name, ctx=None):
        self.name = name
        self._t0 = 0
        self._ctx = ctx

    def __enter__(self):
        if _state.enabled:
            self._t0 = time.perf_counter_ns()
            self._gen = _gen   # mxlint: disable=lock-discipline -- single GIL-atomic int read; a torn window only drops this one span
            if self._ctx is None:
                self._ctx = getattr(_tls, "ids", None)
        return self

    def cancel(self):
        """Drop this span: nothing is recorded at scope exit (e.g. an
        epoch-end StopIteration is not io time)."""
        self._t0 = 0

    def __exit__(self, *exc):
        # record only if telemetry is STILL enabled (a disable() mid-
        # span pins the disabled leg clean) and no reset() started a
        # new accounting window while this span was open
        if self._t0 and _state.enabled and self._gen == _gen:   # mxlint: disable=lock-discipline -- single GIL-atomic int compare; worst case one pre-reset span drops
            _record_span(self.name, self._t0, time.perf_counter_ns(),
                         self._ctx)
        self._t0 = 0
        return False


def span(name, ctx=None):
    """``with telemetry.span("feed"): ...`` — record one host wall-time
    interval into the ring buffer and the per-name histogram. ``ctx``
    attaches explicit causal ids (defaults to the recording thread's
    ambient ``causal()`` scope)."""
    return _Span(name, ctx)


def record_span(name, t0_ns, t1_ns, ctx=None):
    """Record an already-completed interval (``perf_counter_ns``
    endpoints) retroactively — for callers that only learn a span's
    identity AFTER it ended: the collective gate knows which rank it
    waited on (and by how much) only once the wait resolves, yet the
    ``gate_wait`` span must carry that attribution in its ctx."""
    if not _state.enabled:
        return
    _record_span(name, int(t0_ns), int(t1_ns), dict(ctx) if ctx else None)


def _record_span(name, t0_ns, t1_ns, ctx=None):
    # deque.append and dict reads are GIL-atomic so the ring/histogram
    # writes stay lock-free; the cumulative counter is a read-modify-
    # write and takes the lock like every other counter
    _spans.append((name, t0_ns, t1_ns, threading.get_ident(), ctx))   # mxlint: disable=lock-discipline -- GIL-atomic bounded-deque append on the per-batch hot path
    d = _durations.get(name)   # mxlint: disable=lock-discipline -- GIL-atomic dict probe; the insert below re-checks under the lock
    if d is None:
        with _lock:
            d = _durations.setdefault(name, collections.deque(
                maxlen=_DURATIONS_PER_NAME))
    d.append((t1_ns - t0_ns) / 1e9)
    with _lock:
        _span_total[name] = _span_total.get(name, 0) + 1
        _span_seconds[name] = _span_seconds.get(name, 0.0) \
            + (t1_ns - t0_ns) / 1e9


def span_seconds(name):
    """CUMULATIVE wall-seconds recorded under ``name`` since the last
    reset() — unlike the histogram total, not capped by the duration
    ring. The online-MFU denominator."""
    with _lock:
        return _span_seconds.get(name, 0.0)


def span_count(name):
    """CUMULATIVE number of spans recorded under ``name`` since the last
    reset() — unlike ``span_stats()[name]['count']``, not capped by the
    histogram ring, so windowed readers (TelemetryLogger) can tell how
    many new samples landed since their last look."""
    with _lock:
        return _span_total.get(name, 0)


def span_durations(name):
    """Copy of the retained duration samples (seconds, oldest first) for
    one span name — at most the last ``_DURATIONS_PER_NAME`` samples."""
    with _lock:
        d = _durations.get(name)
        return list(d) if d is not None else []


def _percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def span_stats(name=None):
    """Per-span-name wall-time statistics over the retained histogram:
    {name: {count, total_ms, mean_ms, p50_ms, p95_ms, p99_ms, max_ms}}.
    ``name`` restricts to one span name."""
    with _lock:
        items = [(name, list(_durations[name]))] if name is not None \
            and name in _durations else \
            ([] if name is not None else
             [(k, list(v)) for k, v in _durations.items()])
    out = {}
    for k, vals in items:
        s = sorted(vals)
        total = sum(s)
        out[k] = {
            "count": len(s),
            "total_ms": round(total * 1e3, 3),
            "mean_ms": round(total / len(s) * 1e3, 4) if s else 0.0,
            "p50_ms": round(_percentile(s, 50) * 1e3, 4),
            "p95_ms": round(_percentile(s, 95) * 1e3, 4),
            "p99_ms": round(_percentile(s, 99) * 1e3, 4),
            "max_ms": round(s[-1] * 1e3, 4) if s else 0.0,
        }
    return out


# ---------------------------------------------------------------------------
# Program-card registry
# ---------------------------------------------------------------------------

def set_peak_flops(flops):
    """Tell the registry the chip's peak FLOP/s so ``snapshot()`` can
    turn the online sustained-FLOP/s into an MFU fraction. ``None``
    clears it (MFU reads ``None`` again)."""
    global _peak_flops
    with _lock:
        _peak_flops = None if flops is None else float(flops)


def record_program(card):
    """Install one program card (a JSON-safe dict built by
    ``executor.card_from_compiled`` — this module never inspects jax
    objects). ``card["id"]`` keys the registry; a re-record under the
    same id replaces the entry. The registry is bounded at
    ``MAX_PROGRAM_CARDS``: the oldest card is evicted with its
    FLOPs x dispatches folded into the online total."""
    global _programs_dropped_flops
    if not _state.enabled or not isinstance(card, dict) \
            or "id" not in card:
        return
    card.setdefault("dispatches", 0)
    with _lock:
        card["_gen"] = _gen
        _programs[card["id"]] = card
        while len(_programs) > MAX_PROGRAM_CARDS:
            old = _programs.pop(next(iter(_programs)))   # oldest insert
            _programs_dropped_flops += \
                (old.get("flops") or 0.0) * old.get("dispatches", 0)


def program_dispatch(card):
    """One launch of a carded program: bump its dispatch count (under
    the lock — cards are shared with ``programs()`` readers). If a
    reset() opened a new accounting window since the card was
    installed, the count restarts and the card re-registers — so a
    windowed snapshot reads only this window's dispatches."""
    if not _state.enabled or card is None:
        return
    with _lock:
        if card.get("_gen") != _gen:
            card["dispatches"] = 0
            card["_gen"] = _gen
            _programs[card["id"]] = card
        card["dispatches"] = card.get("dispatches", 0) + 1


def card_update(card, **fields):
    """Mutate a (possibly registered) card under the registry lock —
    the only safe way to add fields after ``record_program``, since
    ``programs()`` iterates the shared dict objects."""
    if card is None:
        return
    with _lock:
        card.update(fields)


def card_annotate(card_id, **fields):
    """Annotate a REGISTERED card by id (callers that only hold the
    ``programs()`` copy, e.g. the serving autotuner stamping its chosen
    plan onto the bucket cards). Returns True when the card exists."""
    with _lock:
        card = _programs.get(card_id)
        if card is None:
            return False
        card.update(fields)
        return True


def programs():
    """{card_id: card} copy of the program-card registry (private
    bookkeeping keys stripped — the result is JSON-serializable). The
    per-card copies happen INSIDE the lock: cards are live objects
    that dispatchers mutate under the same lock."""
    with _lock:
        return {k: {kk: vv for kk, vv in c.items()
                    if not kk.startswith("_")}
                for k, c in _programs.items()}


def _online_stats():
    """The live roofline estimate: FLOPs dispatched (card FLOPs x
    dispatch count, plus evicted cards' share) over cumulative
    step-span wall-time. ``mfu`` needs ``set_peak_flops`` — the chip
    ceiling is not knowable from stdlib."""
    with _lock:
        flops = _programs_dropped_flops + sum(
            (c.get("flops") or 0.0) * c.get("dispatches", 0)
            for c in _programs.values())
        step_s = _span_seconds.get("step", 0.0)
        compile_s = _span_seconds.get("jit_compile", 0.0)
        deser_s = _span_seconds.get("jit_deserialize", 0.0)
        # read the ceiling INSIDE the lock: the mfu and peak_flops
        # fields below must come from the same value (a set_peak_flops
        # racing the two bare reads used to be able to split them)
        peak = _peak_flops
    out = {
        "flops_dispatched": flops,
        "step_time_s": round(step_s, 6),
        # first-launch compiles happen INSIDE the step span; reported so
        # readers can judge how much of the window was warmup
        "compile_time_s": round(compile_s, 6),
        # disk-cache loads (compile_cache) — the warm-start counterpart
        "deserialize_time_s": round(deser_s, 6),
        "model_flops_per_s": round(flops / step_s, 3) if step_s else None,
        "peak_flops": peak,
        # unrounded: a CPU-smoke MFU is ~1e-6 and must not read as 0.0
        "mfu": flops / step_s / peak if step_s and peak else None,
    }
    return out


# ---------------------------------------------------------------------------
# Live device-buffer ledger
# ---------------------------------------------------------------------------

def _ledger_release(token):
    """weakref.finalize callback: LOCK-FREE (deque.append is GIL-
    atomic) — see the _ledger_pending note for why taking _lock here
    would deadlock under cyclic GC."""
    try:
        _ledger_pending.append(token)   # mxlint: disable=lock-discipline -- THE finalizer pattern: GIL-atomic append; taking _lock here deadlocks under cyclic GC (the PR 4 bug this rule exists to catch)
    except Exception:       # interpreter-shutdown finalizers must not raise
        pass


def _ledger_release_one_locked(token):
    """Retire ONE live token's charge. Caller holds _lock."""
    rec = _ledger_live.pop(token, None)
    if rec is None:
        return
    st = _ledger.get(rec[0])
    if st is not None:
        st["alive_bytes"] -= rec[1]
        st["alive_count"] -= 1
        bk = st["by_kind"]
        bk[rec[4]] = bk.get(rec[4], 0) - rec[1]
    # a replace-keyed charge drops its reverse-map entry with it (only
    # if the key still maps to THIS token — a re-track may already have
    # claimed it for a newer charge)
    kk = rec[5]
    if kk is not None and _ledger_keyed.get(kk) == token:
        del _ledger_keyed[kk]


def _ledger_drain_locked():
    """Apply pending releases to the counters. Caller holds _lock."""
    while True:
        try:
            token = _ledger_pending.popleft()
        except IndexError:
            return
        _ledger_release_one_locked(token)


def ledger_track(obj, ctx_key, nbytes, shape=None, dtype=None,
                 kind="ndarray", replace=False):
    """Charge ``nbytes`` on context ``ctx_key`` until ``obj`` is
    garbage-collected (weakref.finalize releases the charge). Tracks
    the FRAMEWORK's view — aliasing wrappers (detach, shared _data)
    each count, so alive-bytes is an upper bound of framework-held
    device memory, reconciled against PJRT's own counters by
    ``Storage.ledger_report()``. No-op while disabled (but releases
    always run, so toggling never corrupts the counters).

    ``replace=True`` keys the charge on ``(obj, ctx_key, kind)`` and
    retires any prior live charge under the same key first — the
    re-commit path (a parameter re-placed on its mesh after
    init_params / a plan rebuild) updates its charge instead of
    double-counting the same storage."""
    if not _state.enabled:
        return
    nbytes = int(nbytes)
    token = next(_ledger_seq)
    try:
        weakref.finalize(obj, _ledger_release, token)
    except TypeError:       # obj not weakref-able: count cumulatively only
        token = None
    with _lock:
        _ledger_drain_locked()
        st = _ledger.get(ctx_key)
        if st is None:
            st = _ledger[ctx_key] = {
                "alive_bytes": 0, "alive_count": 0, "peak_bytes": 0,
                "tracked_total": 0, "tracked_bytes_total": 0,
                "by_kind": {}}
        st["tracked_total"] += 1
        st["tracked_bytes_total"] += nbytes
        if token is not None:
            keyed_key = None
            if replace:
                keyed_key = (id(obj), ctx_key, kind)
                prior = _ledger_keyed.pop(keyed_key, None)
                if prior is not None:
                    _ledger_release_one_locked(prior)
                _ledger_keyed[keyed_key] = token
            st["alive_bytes"] += nbytes
            st["alive_count"] += 1
            st["by_kind"][kind] = st["by_kind"].get(kind, 0) + nbytes
            if st["alive_bytes"] > st["peak_bytes"]:
                st["peak_bytes"] = st["alive_bytes"]
            _ledger_live[token] = (ctx_key, nbytes, shape, dtype, kind,
                                   keyed_key)


def ledger():
    """{ctx: {alive_bytes, alive_count, peak_bytes, tracked_total,
    tracked_bytes_total, by_kind}} copy of the per-context ledger
    counters (``by_kind``: live bytes per track kind — e.g. committed
    ``param`` bytes vs in-flight ``shard_put`` batches on a mesh)."""
    with _lock:
        _ledger_drain_locked()
        return {k: dict(v, by_kind=dict(v["by_kind"]))
                for k, v in _ledger.items()}


def ledger_top(n=8):
    """The ``n`` largest LIVE tracked buffers, biggest first:
    [{ctx, nbytes, shape, dtype, kind}] — what the enriched OOM error
    prints so an allocation failure names its suspects."""
    with _lock:
        _ledger_drain_locked()
        live = list(_ledger_live.values())
    live.sort(key=lambda r: -r[1])
    return [{"ctx": r[0], "nbytes": r[1],
             "shape": None if r[2] is None else list(r[2]),
             "dtype": None if r[3] is None else str(r[3]),
             "kind": r[4]} for r in live[:n]]


def online():
    """The live roofline estimate alone (``snapshot()["online"]``)
    without the span-percentile sorts the full snapshot pays — what the
    flight-recorder sampler reads every tick."""
    return _online_stats()


try:
    _HOSTNAME = socket.gethostname()
except OSError:
    _HOSTNAME = "unknown"


def process_identity():
    """The uniform WHO-wrote-this block every banked JSON carries
    (ISSUE 18): rank / process count / recorded-dead peers from the
    dist runtime (env-only when it is absent — import-safe and never
    raises), plus host and pid so artifacts from a shared
    ``MXNET_FLIGHT_DIR`` are attributable without correlating launcher
    logs. Embedded in :func:`snapshot`, flight postmortems, the flight
    sampler's series window and the serving stats surface."""
    try:
        from . import dist as _dist
        ident = {"rank": _dist.rank(),
                 "num_processes": _dist.process_count(),
                 "dead_ranks": list(_dist.dead_ranks())}
    except Exception:
        ident = {"rank": 0, "num_processes": 1, "dead_ranks": []}
    ident["host"] = _HOSTNAME
    ident["pid"] = os.getpid()
    return ident


def snapshot():
    """One self-describing dict: counters + span percentiles + program
    cards + the online MFU estimate + the buffer ledger + the process
    identity block. This is what ``Module.telemetry_snapshot()``
    returns, what ``bench.py`` embeds in the BENCH/MULTICHIP artifacts
    and what ``callback.TelemetryLogger`` diffs per log line. Every
    value is JSON-serializable end to end."""
    return {
        "enabled": _state.enabled,
        "process": process_identity(),
        "counters": counters(),
        "spans": span_stats(),
        "programs": programs(),
        "online": _online_stats(),
        "ledger": ledger(),
    }


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

def mark_trace_start():
    """Stamp the profiler trace-start instant; ``chrome_events()`` then
    exports only spans inside the trace window. Called by
    ``profiler.set_state('run')``."""
    global _trace_start_ns
    _trace_start_ns = time.perf_counter_ns()
    return _trace_start_ns


def _epoch_us(perf_ns):
    return (_ANCHOR_EPOCH_NS + (perf_ns - _ANCHOR_PERF_NS)) / 1e3


def trace_start_epoch_us():
    """Epoch-microsecond instant of the last mark_trace_start() (None
    before any trace ran) — profiler.py aligns host events against the
    device trace's own timebase through this."""
    if _trace_start_ns is None:
        return None
    return _epoch_us(_trace_start_ns)


def _flow_ids(ctx):
    """The flow identities one span's causal ctx binds it to: a request
    id (``req_id`` on request spans, each member of ``req_ids`` on
    batch-level spans) maps to ``req:<n>``; fit-step ids map to
    ``step:<epoch>:<nbatch>``."""
    if not ctx:
        return ()
    out = []
    if ctx.get("req_id") is not None:
        out.append(("req", "req:%s" % ctx["req_id"]))
    for rid in ctx.get("req_ids") or ():
        out.append(("req", "req:%s" % rid))
    if ctx.get("epoch") is not None and ctx.get("nbatch") is not None:
        out.append(("step", "step:%s:%s" % (ctx["epoch"], ctx["nbatch"])))
    return out


# the serving-pipeline order a request FLOW must chain in. Start-time
# order would get it wrong: serve_request is ENTERED at submit (same
# instant as serve_wait), so by start time the chain would terminate at
# serve_d2h and the "request resolved" terminus would never be drawn.
_SERVE_FLOW_RANK = {"serve_wait": 0,
                    "serve_prefill": 1,
                    "serve_batch": 2, "serve_decode_step": 2,
                    "serve_d2h": 3, "serve_detokenize": 3,
                    "serve_request": 4}


def chrome_events(pid=None, since_trace_start=True):
    """Render retained host spans as chrome://tracing complete events
    (``ph: "X"``, ``ts``/``dur`` in microseconds, epoch timebase) plus
    the process/thread metadata rows that label the track "mxnet_tpu
    host" in perfetto, plus FLOW events (``ph: "s"/"t"/"f"``) linking
    the spans that share one causal id — one request's serve_wait →
    serve_batch → serve_d2h → serve_request across the submit/coalesce/
    resolve threads, one fit step's feed → step → opt spans — so
    perfetto draws the request's/step's path as arrows.
    ``since_trace_start=True`` keeps only spans that began after the
    last ``mark_trace_start()`` (everything, if no trace was
    started)."""
    if pid is None:
        pid = os.getpid()
    with _lock:
        spans = list(_spans)
    t0 = _trace_start_ns if since_trace_start else None
    ident = process_identity()
    track = "mxnet_tpu host"
    if ident["num_processes"] > 1:
        track = "mxnet_tpu %s (rank %d)" % (ident["host"], ident["rank"])
    events = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": track},
    }, {
        "ph": "M", "name": "process_sort_index", "pid": pid, "tid": 0,
        "args": {"sort_index": -1},
    }]
    tids = set()
    flows = {}            # flow id -> (label, [(s_ns, tid), ...])
    for name, s_ns, e_ns, tid, ctx in spans:
        if t0 is not None and s_ns < t0:
            continue
        tids.add(tid)
        ev = {
            "ph": "X", "cat": "host", "name": name,
            "pid": pid, "tid": tid,
            "ts": round(_epoch_us(s_ns), 3),
            "dur": round((e_ns - s_ns) / 1e3, 3),
        }
        if ctx:
            ev["args"] = dict(ctx)
        events.append(ev)
        for label, fid in _flow_ids(ctx):
            # request flows chain in PIPELINE order (wait -> batch ->
            # d2h -> request), not start order — serve_request opens at
            # submit, so its start sorts next to serve_wait; its node
            # binds near the span END (the resolution instant), which
            # also keeps the drawn arrows chronologically forward.
            # Other flows (fit steps) chain by start time.
            rank = _SERVE_FLOW_RANK.get(name, -1) if label == "req" \
                else -1
            bind_ns = s_ns if name != "serve_request" \
                else max(s_ns, e_ns - 1000)
            flows.setdefault(fid, (label, []))[1].append(
                (rank, bind_ns, tid))
    for fid, (label, members) in flows.items():
        if len(members) < 2:
            continue          # an arrow needs two ends
        members.sort()       # (rank, bind_ns, tid): pipeline order,
                             # then time within a rank
        last = len(members) - 1
        for i, (_rank, bind_ns, tid) in enumerate(members):
            # flow binding: ts inside the slice on the same thread —
            # a slice's own start (or a point just before its end, for
            # the serve_request terminus) is inside by definition
            ev = {
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
                "cat": "flow", "name": label, "id": fid,
                "pid": pid, "tid": tid,
                "ts": round(_epoch_us(bind_ns), 3),
            }
            if i == last:
                ev["bp"] = "e"   # bind the finish to the enclosing slice
            events.append(ev)
    for tid in tids:
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": "host thread %d" % tid},
        })
    return events
