"""Unified runtime telemetry: counters, host-span tracing, dispatch taps.

No reference counterpart — the reference's only runtime window was the
engine profiler's device spans (src/engine/profiler.cc). On a remoted
PJRT backend the HOST side (feed, shard_put, dispatch, fallback
decisions, blocking syncs) is where throughput goes to die — it is what
hid the 14x ``Module.fit`` gap until round 5 (PERF.md) — so this module
is the standing instrument every perf PR reads from:

* a **counter registry** — jitted-program dispatches by kind, jit-cache
  compiles vs. hits per ``_GraphProgram`` entry point, fused-step
  fallback events keyed by their stable ``FusedFallback.code``,
  host->device transfer bytes, blocking host syncs, kvstore traffic;
* **host-side span tracing** — ``with telemetry.span("feed"): ...``
  records wall-time intervals into a bounded ring buffer with a
  per-name duration histogram and a p50/p95/p99 ``snapshot()`` API;
* a **multi-subscriber dispatch registry** — ``on_dispatch(cb)`` /
  ``remove_dispatch(cb)`` replaces the old single-slot
  ``executor.dispatch_hook`` global (which probe, tests and telemetry
  silently clobbered off each other; the legacy name still works as a
  back-compat shim read by ``executor.record_dispatch``);
* **chrome-trace export** — ``chrome_events()`` renders the span ring
  as chrome://tracing ``X`` events; ``profiler.py`` merges them into
  the XLA device dump so host and device timelines land in ONE
  perfetto-loadable JSON.

Everything here is stdlib-only (no jax import) and cheap when disabled:
``MXNET_TELEMETRY=0`` (or ``disable()``) reduces every span to two
attribute reads and every counter to one branch. Counters and spans are
process-global — the fit loop, the kvstore and the io pipeline all feed
one registry, which is exactly what makes the merged trace readable.
"""
from __future__ import annotations

import collections
import os
import threading
import time

__all__ = [
    "enabled", "enable", "disable", "reset",
    "counter_inc", "counters", "snapshot", "span", "span_stats",
    "span_count", "span_durations",
    "on_dispatch", "remove_dispatch", "dispatch_event",
    "record_jit", "record_fallback", "record_transfer",
    "record_host_sync", "chrome_events", "mark_trace_start",
    "SPAN_RING_SIZE", "FIT_PHASE_SPANS",
]

# ring capacities: bound memory for arbitrarily long training runs. The
# span ring keeps the most recent intervals for chrome export; duration
# histograms keep more samples per name so percentiles stay meaningful
# after the ring has wrapped.
SPAN_RING_SIZE = 4096
_DURATIONS_PER_NAME = 4096

# the fit-loop phase span names — the ONE list the bench/probe artifact
# summaries filter on, kept next to the code that records them so the
# BENCH/MULTICHIP accountings can't silently diverge
FIT_PHASE_SPANS = ("fit_batch", "feed", "step", "shard_put",
                   "metric_update", "metric_fetch", "opt_update",
                   "io_next", "callbacks", "epoch_sync",
                   "kv_push", "kv_pull")


class _State:
    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = os.environ.get("MXNET_TELEMETRY", "1") not in (
            "0", "false")


_state = _State()
_lock = threading.Lock()
_counters = {}
# span ring: (name, start_ns, end_ns, thread_id) in perf_counter_ns time
_spans = collections.deque(maxlen=SPAN_RING_SIZE)
_durations = {}          # name -> deque of duration seconds
_span_total = {}         # name -> cumulative span count (uncapped)
_dispatch_subs = []      # multi-subscriber dispatch registry
_gen = 0                 # bumped by reset(): spans straddling a reset
                         # belong to the OLD window and must not leak
                         # into the freshly cleared registry

# perf_counter<->epoch anchor, taken once at import: spans are stamped
# in the monotonic perf_counter timebase (immune to clock steps); the
# chrome exporter maps them back to epoch microseconds through this
# anchor so they can align with the device trace
_ANCHOR_PERF_NS = time.perf_counter_ns()
_ANCHOR_EPOCH_NS = time.time_ns()

# perf_counter_ns stamp of the last profiler trace start (chrome export
# filters to spans inside the trace window)
_trace_start_ns = None


# ---------------------------------------------------------------------------
# Enable/disable
# ---------------------------------------------------------------------------

def enabled():
    """Whether spans and counters record (default on; MXNET_TELEMETRY=0
    starts disabled). Dispatch SUBSCRIBERS fire regardless — they were
    installed explicitly."""
    return _state.enabled


def enable():
    _state.enabled = True


def disable():
    _state.enabled = False


def reset():
    """Clear every counter, span and histogram (subscribers stay).
    Spans currently OPEN on any thread are dropped at their exit — a
    pre-reset interval must not appear in the new accounting window."""
    global _gen
    with _lock:
        _gen += 1
        _counters.clear()
        _spans.clear()
        _durations.clear()
        _span_total.clear()


# ---------------------------------------------------------------------------
# Counter registry
# ---------------------------------------------------------------------------

def counter_inc(name, n=1):
    """Add ``n`` to counter ``name`` (no-op while disabled)."""
    if not _state.enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def counters():
    """Snapshot copy of the counter registry."""
    with _lock:
        return dict(_counters)


def record_jit(kind, hit):
    """One ``_GraphProgram``/updater jit-cache lookup: ``hit=False`` is
    a program build (trace + XLA compile on first execution), ``hit=True``
    a cached-program reuse. Fed by executor.py — the compile-vs-hit ratio
    is the recompile-storm detector."""
    if not _state.enabled:
        return
    what = "hit" if hit else "compile"
    with _lock:
        _counters["jit.%s" % what] = _counters.get("jit.%s" % what, 0) + 1
        k = "jit.%s.%s" % (what, kind)
        _counters[k] = _counters.get(k, 0) + 1


def record_fallback(code):
    """One fused-step fallback event, keyed by the stable
    ``FusedFallback.code`` (module/base_module.FUSED_FALLBACK_CODES)."""
    counter_inc("fused_fallback.%s" % code)


def record_transfer(nbytes, direction="h2d"):
    """Host<->device transfer accounting (bytes + event count)."""
    if not _state.enabled:
        return
    with _lock:
        _counters["transfer.%s_bytes" % direction] = \
            _counters.get("transfer.%s_bytes" % direction, 0) + int(nbytes)
        _counters["transfer.%s_count" % direction] = \
            _counters.get("transfer.%s_count" % direction, 0) + 1


def record_host_sync(what="host"):
    """One BLOCKING host synchronisation (asnumpy/wait_to_read/metric
    flush) — the async-pipeline stalls PERF.md hunts for."""
    if not _state.enabled:
        return
    with _lock:
        _counters["host_sync.blocking"] = \
            _counters.get("host_sync.blocking", 0) + 1
        k = "host_sync.%s" % what
        _counters[k] = _counters.get(k, 0) + 1


# ---------------------------------------------------------------------------
# Dispatch registry (multi-subscriber; replaces the single-slot hook)
# ---------------------------------------------------------------------------

def on_dispatch(cb):
    """Subscribe ``cb(kind)`` to every jitted-program dispatch
    (``executor.record_dispatch``). Unlike the legacy single-slot
    ``executor.dispatch_hook`` global, any number of subscribers coexist
    — the probe, tests and telemetry no longer clobber each other.
    Returns ``cb`` for symmetric ``remove_dispatch(cb)``."""
    with _lock:
        if cb not in _dispatch_subs:
            _dispatch_subs.append(cb)
    return cb


def remove_dispatch(cb):
    """Unsubscribe a callback; unknown callbacks are ignored."""
    with _lock:
        try:
            _dispatch_subs.remove(cb)
        except ValueError:
            pass


def dispatch_event(kind):
    """Fan one dispatch out to the counter registry and every
    subscriber. Called by ``executor.record_dispatch`` — the ONE
    dispatch-reporting entry point (tools/run_checks.sh lints that no
    other site grows a raw hook call)."""
    if _state.enabled:
        with _lock:
            k = "dispatch.%s" % kind
            _counters[k] = _counters.get(k, 0) + 1
    if _dispatch_subs:
        for cb in list(_dispatch_subs):
            cb(kind)


def dispatch_counts():
    """{kind: count} view of the dispatch counters (the probe's
    per-batch dispatch accounting reads this instead of installing its
    own hook)."""
    with _lock:
        return {k[len("dispatch."):]: v for k, v in _counters.items()
                if k.startswith("dispatch.")}


# ---------------------------------------------------------------------------
# Host-side span tracing
# ---------------------------------------------------------------------------

class _Span:
    """Reentrant-per-instance-free timing scope; ~two perf_counter_ns
    calls + two deque appends when enabled, two attribute reads when
    disabled."""
    __slots__ = ("name", "_t0", "_gen")

    def __init__(self, name):
        self.name = name
        self._t0 = 0

    def __enter__(self):
        if _state.enabled:
            self._t0 = time.perf_counter_ns()
            self._gen = _gen
        return self

    def cancel(self):
        """Drop this span: nothing is recorded at scope exit (e.g. an
        epoch-end StopIteration is not io time)."""
        self._t0 = 0

    def __exit__(self, *exc):
        # record only if telemetry is STILL enabled (a disable() mid-
        # span pins the disabled leg clean) and no reset() started a
        # new accounting window while this span was open
        if self._t0 and _state.enabled and self._gen == _gen:
            _record_span(self.name, self._t0, time.perf_counter_ns())
        self._t0 = 0
        return False


def span(name):
    """``with telemetry.span("feed"): ...`` — record one host wall-time
    interval into the ring buffer and the per-name histogram."""
    return _Span(name)


def _record_span(name, t0_ns, t1_ns):
    # deque.append and dict reads are GIL-atomic so the ring/histogram
    # writes stay lock-free; the cumulative counter is a read-modify-
    # write and takes the lock like every other counter
    _spans.append((name, t0_ns, t1_ns, threading.get_ident()))
    d = _durations.get(name)
    if d is None:
        with _lock:
            d = _durations.setdefault(name, collections.deque(
                maxlen=_DURATIONS_PER_NAME))
    d.append((t1_ns - t0_ns) / 1e9)
    with _lock:
        _span_total[name] = _span_total.get(name, 0) + 1


def span_count(name):
    """CUMULATIVE number of spans recorded under ``name`` since the last
    reset() — unlike ``span_stats()[name]['count']``, not capped by the
    histogram ring, so windowed readers (TelemetryLogger) can tell how
    many new samples landed since their last look."""
    return _span_total.get(name, 0)


def span_durations(name):
    """Copy of the retained duration samples (seconds, oldest first) for
    one span name — at most the last ``_DURATIONS_PER_NAME`` samples."""
    with _lock:
        d = _durations.get(name)
        return list(d) if d is not None else []


def _percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def span_stats(name=None):
    """Per-span-name wall-time statistics over the retained histogram:
    {name: {count, total_ms, mean_ms, p50_ms, p95_ms, p99_ms, max_ms}}.
    ``name`` restricts to one span name."""
    with _lock:
        items = [(name, list(_durations[name]))] if name is not None \
            and name in _durations else \
            ([] if name is not None else
             [(k, list(v)) for k, v in _durations.items()])
    out = {}
    for k, vals in items:
        s = sorted(vals)
        total = sum(s)
        out[k] = {
            "count": len(s),
            "total_ms": round(total * 1e3, 3),
            "mean_ms": round(total / len(s) * 1e3, 4) if s else 0.0,
            "p50_ms": round(_percentile(s, 50) * 1e3, 4),
            "p95_ms": round(_percentile(s, 95) * 1e3, 4),
            "p99_ms": round(_percentile(s, 99) * 1e3, 4),
            "max_ms": round(s[-1] * 1e3, 4) if s else 0.0,
        }
    return out


def snapshot():
    """One self-describing dict: counters + span percentiles. This is
    what ``Module.telemetry_snapshot()`` returns, what ``bench.py``
    embeds in the BENCH/MULTICHIP artifacts and what
    ``callback.TelemetryLogger`` diffs per log line."""
    return {
        "enabled": _state.enabled,
        "counters": counters(),
        "spans": span_stats(),
    }


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

def mark_trace_start():
    """Stamp the profiler trace-start instant; ``chrome_events()`` then
    exports only spans inside the trace window. Called by
    ``profiler.set_state('run')``."""
    global _trace_start_ns
    _trace_start_ns = time.perf_counter_ns()
    return _trace_start_ns


def _epoch_us(perf_ns):
    return (_ANCHOR_EPOCH_NS + (perf_ns - _ANCHOR_PERF_NS)) / 1e3


def trace_start_epoch_us():
    """Epoch-microsecond instant of the last mark_trace_start() (None
    before any trace ran) — profiler.py aligns host events against the
    device trace's own timebase through this."""
    if _trace_start_ns is None:
        return None
    return _epoch_us(_trace_start_ns)


def chrome_events(pid=None, since_trace_start=True):
    """Render retained host spans as chrome://tracing complete events
    (``ph: "X"``, ``ts``/``dur`` in microseconds, epoch timebase) plus
    the process/thread metadata rows that label the track "mxnet_tpu
    host" in perfetto. ``since_trace_start=True`` keeps only spans that
    began after the last ``mark_trace_start()`` (everything, if no trace
    was started)."""
    if pid is None:
        pid = os.getpid()
    with _lock:
        spans = list(_spans)
    t0 = _trace_start_ns if since_trace_start else None
    events = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": "mxnet_tpu host"},
    }, {
        "ph": "M", "name": "process_sort_index", "pid": pid, "tid": 0,
        "args": {"sort_index": -1},
    }]
    tids = set()
    for name, s_ns, e_ns, tid in spans:
        if t0 is not None and s_ns < t0:
            continue
        tids.add(tid)
        events.append({
            "ph": "X", "cat": "host", "name": name,
            "pid": pid, "tid": tid,
            "ts": round(_epoch_us(s_ns), 3),
            "dur": round((e_ns - s_ns) / 1e3, 3),
        })
    for tid in tids:
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": "host thread %d" % tid},
        })
    return events
